"""TCP messenger backend: the framework over real sockets.

The AsyncMessenger/posix analogue (ref: src/msg/async/AsyncMessenger.cc,
PosixStack — event-driven sockets with per-peer Connections).  Frames
carry the versioned typed encoding from `ceph_tpu.msg.encoding`
(preamble + struct payload + crc32c epilogue, the frames_v2 model —
ref: src/msg/async/frames_v2.h:58-151); decoding constructs only
registered wire structs and TLV primitives, never code.  Same
dispatcher surface as the in-process transport
(ceph_tpu.msg.messenger), so every daemon — mon, OSD, mgr, client —
runs unmodified over localhost or a LAN, one process per daemon (the
reference's deployment model).

Addressing: a static name -> (host, port) map (the monmap analogue,
ref: src/mon/MonMap.h + per-daemon bind addrs from the config).

Delivery semantics match LocalNetwork: per-peer FIFO, best-effort;
a failed/refused connection reports ms_handle_reset to the sender.
"""
from __future__ import annotations

import socket
import struct
import threading
import time

from ..common.lockdep import make_lock

from ..common.log import dout
from ..common.racecheck import shared_state
from .encoding import WireError, decode_message, encode_message
from .messenger import Connection, Dispatcher, Message

_HDR = struct.Struct("!I")
MAX_FRAME = 1 << 30


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_HDR.pack(len(payload)) + payload)


def recv_frame(sock: socket.socket) -> bytes | None:
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    (n,) = _HDR.unpack(hdr)
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    return _recv_exact(sock, n)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


class TcpNet:
    """The monmap analogue: name -> (host, port) for every entity.
    Passing one of these as the `network` to Messenger.create selects
    the TCP backend (ref: MonMap + per-daemon bind addrs).

    `secure_secret` switches every endpoint created on this net into
    secure wire mode (ref: msgr v2 SECURE mode, crypto_onwire.cc):
    frames are sealed with authenticated encryption derived from the
    cluster secret — see ceph_tpu.msg.secure for the construction."""

    def __init__(self, addr_map: dict[str, tuple[str, int]],
                 secure_secret: str | bytes | None = None,
                 compress: str | None = None,
                 compress_min: int = 4096,
                 faults=None):
        self.addr_map = dict(addr_map)
        self.secure_secret = secure_secret
        #: on-wire compression (ref: msgr v2 compression negotiation,
        #: ms_osd_compress_mode): frames above compress_min bytes are
        #: compressed with the named registry algorithm
        self.compress = compress
        self.compress_min = compress_min
        #: optional shared FaultPlane — every endpoint created on this
        #: net intercepts its sends through it (drop/partition/delay/
        #: dup; reorder needs a queue transport and is a no-op here)
        self.faults = faults


# the connection maps are shared between the send path (any caller
# thread), the accept loop, and every per-socket reader thread —
# racecheck asserts each access holds self._lock
@shared_state(only=("_out", "_learned", "_accepted", "_sessions"),
              mutating=("_out", "_learned", "_accepted", "_sessions"))
class TcpMessenger:
    """One endpoint bound to addr_map[name]
    (ref: Messenger::bind + AsyncMessenger accept loop)."""

    def __init__(self, addr_map: dict[str, tuple[str, int]], name: str,
                 secure_secret: str | bytes | None = None,
                 compress: str | None = None,
                 compress_min: int = 4096,
                 faults=None):
        self.name = name
        self.addr_map = dict(addr_map)
        #: send-side fault intercept (ceph_tpu.msg.faults.FaultPlane):
        #: consulted before every socket write, so a partitioned or
        #: lossy link fails here exactly like the in-process backend
        self.faults = faults
        # secure wire mode (ref: frames_v2 SECURE): every CONNECTION
        # runs its own KEX and seals under per-session, per-direction
        # keys (msg/secure.py SecureConn; VERDICT r3 #4 — one captured
        # or compromised session no longer decrypts any other)
        self._secure_secret = secure_secret
        #: socket -> SecureConn session state
        self._sessions: dict = {}
        # on-wire compression (ref: msgr v2 compression / the
        # compressor registry the reference wires into the messenger).
        # Layering matches the reference: compress, THEN seal —
        # ciphertext doesn't compress.  BOTH endpoints must share the
        # setting (it travels in the monmap via "ms_compress", like
        # ms_secure_mode) — the flag byte is only present when on.
        self._compress = compress
        self._compress_min = compress_min
        if compress is not None:
            from ..compressor import registry as _creg
            _creg.create(compress)     # fail fast on unknown algs
        self.dispatchers: list[Dispatcher] = []
        self._lock = make_lock(f"msgr.tcp.{name}")
        self._out: dict[str, socket.socket] = {}   # peer -> conn
        # connections learned from inbound traffic: lets us answer
        # peers with no monmap address (clients are not in the monmap;
        # the reference learns entity addrs from the connection banner
        # and replies over the accepted socket)
        self._learned: dict[str, socket.socket] = {}
        self._running = False
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        #: sockets accepted from peers — closed on shutdown so their
        #: reader threads exit and the kernel releases the port
        self._accepted: list[socket.socket] = []
        self._threads: list[threading.Thread] = []
        self._seq = 0
        # cephx hooks (same surface as the in-process messenger)
        self.auth_signer = None
        self.auth_verifier = None
        # crash capture (same surface as the in-process messenger)
        self.crash_hook = None

    # -- messenger surface ----------------------------------------------
    def add_dispatcher(self, d: Dispatcher) -> None:
        self.dispatchers.append(d)

    def connect(self, peer: str) -> Connection:
        return Connection(self, peer)

    def start(self) -> None:
        self._running = True
        addr = self.addr_map.get(self.name)
        if addr is None:
            # client-only endpoint: no listener; replies arrive over
            # the connections we initiate (ref: clients don't bind —
            # Objecter traffic flows over its outgoing Connections)
            return
        host, port = addr
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        t = threading.Thread(target=self._accept_loop,
                             name=f"tcp-accept-{self.name}", daemon=True)
        t.start()
        self._accept_thread = t
        self._threads.append(t)

    def poll(self, max_msgs: int = 0) -> int:
        """Socket reads deliver on their own threads; nothing to pump
        (API compat with the in-process transport)."""
        return 0

    def shutdown(self) -> None:
        self._running = False
        with self._lock:
            socks = list(self._out.values()) + self._accepted
            self._out.clear()
            self._learned.clear()
            self._accepted = []
            self._sessions.clear()
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        if self._listener is not None:
            # wake the thread blocked in accept() FIRST: a close alone
            # leaves the in-syscall reference holding the socket open
            # (the port stays in LISTEN and a revived daemon on the
            # same addr gets EADDRINUSE)
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
            if self._accept_thread is not None and \
                    self._accept_thread is not threading.current_thread():
                self._accept_thread.join(timeout=5.0)

    # -- send ------------------------------------------------------------
    def _secure_handshake(self, sock) -> object | None:
        """Initiator side of the per-connection KEX: send our share,
        the reader thread ingests the responder's and signals ready.
        Returns the established SecureConn or None."""
        from .secure import SecureConn
        sc = SecureConn(self._secure_secret, initiator=True)
        self._sessions[sock] = sc
        try:
            send_frame(sock, sc.kex_frame())
        except OSError:
            return None
        return sc

    def _wait_session(self, sock) -> bool:
        """Wait for the socket's KEX to complete with the messenger
        lock RELEASED — a hung peer's handshake must not stall sends
        to every other peer.  Caller holds self._lock."""
        sc = self._sessions.get(sock)
        if sc is None:
            return False
        if sc.established:
            return True
        self._lock.release()
        try:
            return sc.ready.wait(5.0)
        finally:
            self._lock.acquire()

    def _seal_for(self, sock, payload: bytes) -> bytes | None:
        """Seal under the socket's established session; None = no
        session, or an INITIATOR-side connection due for rekey
        (rotation is initiator-driven: a responder forcing it on a
        learned socket would drop the in-flight reply with no way to
        reconnect to a listener-less client)."""
        sc = self._sessions.get(sock)
        if sc is None or not sc.established:
            return None
        from .secure import REKEY_FRAMES
        if sc.initiator and sc.send_ctr >= REKEY_FRAMES:
            return None          # rotate: reconnect runs a fresh KEX
        return sc.seal(payload)

    def _send_sealed(self, sock, payload: bytes) -> None:
        """One framing contract for every send path: seal when secure
        (waiting out a pending KEX first), raise OSError on failure."""
        if self._secure_secret is not None:
            if not self._wait_session(sock):
                raise OSError("secure session unavailable")
            sealed = self._seal_for(sock, payload)
            if sealed is None:
                raise OSError("secure session unavailable")
            send_frame(sock, sealed)
        else:
            send_frame(sock, payload)

    def _send(self, peer: str, msg: Message) -> bool:
        import dataclasses
        eff = None
        if self.faults is not None:
            # decide (and sleep out an injected delay) BEFORE taking
            # the lock: a delayed link must not stall unrelated peers
            eff = self.faults.decide(self.name, peer, msg.type_name)
            if eff.dropped:
                if eff.reset:
                    self.handle_reset(peer)
                return False
            if eff.delay > 0.0:
                time.sleep(min(eff.delay, 1.0))
        with self._lock:
            self._seq += 1
            msg = dataclasses.replace(msg, src=self.name, seq=self._seq)
            try:
                # sign() canonicalizes through the wire codec too, so
                # it must sit inside the WireError net with the encode
                if self.auth_signer is not None:
                    msg = self.auth_signer.sign(msg)
                payload = encode_message(msg)
                if self._compress is not None:
                    if len(payload) >= self._compress_min:
                        from .. import compressor
                        payload = b"\x01" + compressor.compress(
                            payload, self._compress)
                    else:
                        payload = b"\x00" + payload
            except WireError as ex:
                dout("ms", 0).write("%s: unencodable %s: %s", self.name,
                                    msg.type_name, ex)
                return False
            learned = False
            sock = self._out.get(peer)
            if sock is None and peer not in self.addr_map:
                sock = self._learned.get(peer)
                learned = sock is not None
            fresh = False
            if sock is None:
                sock = self._connect_peer(peer)
                if sock is None:
                    self.handle_reset(peer)
                    return False
                fresh = True
                self._out[peer] = sock
                if self._secure_secret is not None:
                    self._secure_handshake(sock)
                self._spawn_reader(sock)
            try:
                self._send_sealed(sock, payload)
                if eff is not None and eff.dup:
                    # injected duplication: same frame, same seq — the
                    # receiver sees a TCP-retransmit-style replay
                    self._send_sealed(sock, payload)
                return True
            except OSError:
                (self._learned if learned else self._out).pop(peer, None)
                self._sessions.pop(sock, None)
                try:
                    sock.close()
                except OSError:
                    pass
                # a cached socket may be stale (the peer restarted —
                # e.g. an OSD process kill -9'd and revived on the same
                # addr — or its secure session is due for rotation):
                # reconnect once and resend before declaring the peer
                # reset, or a mon's map push to a rebooted daemon is
                # silently lost (ref: AsyncConnection reconnect)
                if not fresh and peer in self.addr_map:
                    sock = self._connect_peer(peer)
                    if sock is not None:
                        self._out[peer] = sock
                        if self._secure_secret is not None:
                            self._secure_handshake(sock)
                        self._spawn_reader(sock)
                        try:
                            self._send_sealed(sock, payload)
                            return True
                        except OSError:
                            self._out.pop(peer, None)
                            self._sessions.pop(sock, None)
                            try:
                                sock.close()
                            except OSError:
                                pass
        self.handle_reset(peer)
        return False

    def _connect_peer(self, peer: str) -> socket.socket | None:
        addr = self.addr_map.get(peer)
        if addr is None:
            return None
        try:
            s = socket.create_connection(tuple(addr), timeout=5.0)
            s.settimeout(None)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return s
        except OSError:
            return None

    # -- receive ---------------------------------------------------------
    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._accepted.append(conn)
                if self._secure_secret is not None:
                    # inside the lock: the send path reads _sessions
                    # under it concurrently (racecheck-audited)
                    from .secure import SecureConn
                    self._sessions[conn] = SecureConn(
                        self._secure_secret, initiator=False)
            self._spawn_reader(conn, learn=True)

    def _spawn_reader(self, conn: socket.socket,
                      learn: bool = False) -> None:
        """Every socket gets a reader — outbound ones too, so a peer
        that answers over OUR connection (it has no address for us) is
        heard."""
        t = threading.Thread(target=self._read_loop,
                             args=(conn, learn), daemon=True)
        t.start()
        self._threads.append(t)

    def _read_loop(self, conn: socket.socket, learn: bool) -> None:
        peer = None
        with self._lock:
            sc = self._sessions.get(conn)
        try:
            while self._running:
                frame = recv_frame(conn)
                if frame is None:
                    break
                if self._secure_secret is not None:
                    if sc is None:
                        break
                    if not sc.established:
                        # handshake leg: ingest the peer's KEX; the
                        # responder answers with its own share
                        if not sc.ingest_kex(frame):
                            dout("ms", 1).write(
                                "%s: bad KEX frame — dropping "
                                "connection", self.name)
                            break
                        if not sc.initiator:
                            send_frame(conn, sc.kex_frame())
                        continue
                    frame = sc.open(frame)
                    if frame is None:
                        dout("ms", 1).write(
                            "%s: secure frame failed authentication "
                            "— dropping connection", self.name)
                        break
                if self._compress is not None:
                    if not frame:
                        break
                    if frame[0] == 1:
                        from .. import compressor
                        try:
                            # cap post-decompression size: a small
                            # frame must not inflate into an OOM bomb
                            frame = compressor.decompress(
                                frame[1:], max_len=MAX_FRAME)
                        except Exception as ex:
                            dout("ms", 1).write(
                                "%s: bad compressed frame: %s — "
                                "dropping connection", self.name, ex)
                            break
                    else:
                        frame = frame[1:]
                msg = decode_message(frame)
                # authenticate BEFORE learning: otherwise a forged
                # frame could hijack the learned reply route for the
                # entity it spoofs (verified by the cephx e2e drive)
                if self.auth_verifier is not None and \
                        not self.auth_verifier.verify(msg):
                    dout("ms", 1).write(
                        "%s: dropping unauthenticated %s from %s",
                        self.name, msg.type_name, msg.src)
                    continue
                if learn:
                    # every verified frame refreshes the route (a
                    # reset elsewhere may have dropped the mapping)
                    with self._lock:
                        self._learned[msg.src] = conn
                peer = msg.src
                self._deliver_verified(msg)
        except (OSError, ValueError) as ex:
            if self._running:      # shutdown closes sockets under us
                dout("ms", 1).write("%s: read error from %s: %s",
                                    self.name, peer, ex)
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                self._sessions.pop(conn, None)
                # prune dead accepted sockets: a long-lived endpoint
                # (a mon taking beacons across thrash rounds) must
                # not accumulate one entry per past connection
                try:
                    self._accepted.remove(conn)
                except ValueError:
                    pass
            if peer is not None:
                with self._lock:
                    if self._learned.get(peer) is conn:
                        del self._learned[peer]
                if self._running:
                    self.handle_reset(peer)

    def _deliver_verified(self, msg: Message) -> None:
        for d in self.dispatchers:
            try:
                if d.ms_dispatch(msg):
                    return
            except Exception as ex:
                import traceback
                dout("ms", 0).write("dispatch error on %s: %s",
                                    self.name, traceback.format_exc())
                if self.crash_hook is not None:
                    try:
                        self.crash_hook(ex)
                    except Exception as hex_:
                        # capture must never re-crash the reader
                        dout("ms", 0).write(
                            "%s: crash hook failed: %s", self.name,
                            hex_)
                return
        dout("ms", 1).write("%s: unhandled message %s from %s",
                            self.name, msg.type_name, msg.src)

    def handle_reset(self, peer: str) -> None:
        for d in self.dispatchers:
            d.ms_handle_reset(peer)


def pick_free_ports(n: int, host: str = "127.0.0.1") -> list[int]:
    """Ephemeral ports for a test/launcher monmap."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind((host, 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports
