"""osdmaptool equivalent: build simple maps and bulk-map all PGs.

CLI port of the reference's test/inspection tool
(ref: src/tools/osdmaptool.cc: --createsimple :31, --test-map-pgs
:38,:198, stats block :491-615) with the bulk mapping computed by the
batched vmapped CRUSH engine (ceph_tpu.osd.mapping.OSDMapMapping)
instead of a per-PG loop.

Usage:
  python -m ceph_tpu.tools.osdmaptool --createsimple 100 /tmp/om.json
  python -m ceph_tpu.tools.osdmaptool /tmp/om.json --test-map-pgs [--pg-num N]
  python -m ceph_tpu.tools.osdmaptool /tmp/om.json --test-map-pgs-dump
"""
from __future__ import annotations

import argparse
import json
import math
import sys
import time

import numpy as np

from ..crush.codec import crush_from_json, crush_to_json
from ..crush.types import CRUSH_ITEM_NONE
from ..osd.mapping import OSDMapMapping
from ..osd.osdmap import OSDMap
from ..osd.types import PG, PGPool


def save_map(m: OSDMap, path: str) -> None:
    """Serialize the placement-relevant state as JSON."""
    data = {
        "epoch": m.epoch,
        "max_osd": m.max_osd,
        "osd_state": m.osd_state,
        "osd_weight": m.osd_weight,
        "osd_primary_affinity": m.osd_primary_affinity,
        "pools": {str(k): vars(p).copy() for k, p in m.pools.items()},
        "pool_names": {str(k): v for k, v in m.pool_names.items()},
        "pool_max": m.pool_max,
        "pg_upmap": [[pg.pool, pg.ps, osds]
                     for pg, osds in m.pg_upmap.items()],
        "pg_upmap_items": [[pg.pool, pg.ps, [list(p) for p in items]]
                           for pg, items in m.pg_upmap_items.items()],
        "pg_temp": [[pg.pool, pg.ps, osds]
                    for pg, osds in m.pg_temp.items()],
        "primary_temp": [[pg.pool, pg.ps, p]
                         for pg, p in m.primary_temp.items()],
        "erasure_code_profiles": m.erasure_code_profiles,
        # shared codec (ceph_tpu.crush.codec) — same crush encoding as
        # crushtool map files, choose_args included
        "crush": crush_to_json(m.crush),
    }
    with open(path, "w") as f:
        json.dump(data, f)


def load_map(path: str) -> OSDMap:
    with open(path) as f:
        data = json.load(f)
    m = OSDMap()
    m.epoch = data["epoch"]
    m.max_osd = data["max_osd"]
    m.osd_state = list(data["osd_state"])
    m.osd_weight = list(data["osd_weight"])
    m.osd_primary_affinity = data.get("osd_primary_affinity")
    for k, pd in data["pools"].items():
        pool = PGPool()
        for attr, v in pd.items():
            setattr(pool, attr, v)
        m.pools[int(k)] = pool
    m.pool_names = {int(k): v for k, v in data["pool_names"].items()}
    m.pool_max = data.get("pool_max", max(m.pools, default=-1))
    for pool, ps, osds in data.get("pg_upmap", []):
        m.pg_upmap[PG(pool, ps)] = list(osds)
    for pool, ps, items in data.get("pg_upmap_items", []):
        m.pg_upmap_items[PG(pool, ps)] = [tuple(p) for p in items]
    for pool, ps, osds in data.get("pg_temp", []):
        m.pg_temp[PG(pool, ps)] = list(osds)
    for pool, ps, p in data.get("primary_temp", []):
        m.primary_temp[PG(pool, ps)] = p
    m.erasure_code_profiles = data.get("erasure_code_profiles", {})
    m.crush = crush_from_json(data["crush"])
    return m


def test_map_pgs(m: OSDMap, pool_filter: int, pg_num: int,
                 dump: bool) -> None:
    """Stats block of osdmaptool.cc:491-615 (same output shape).
    --pg-num is a test-only override: operates on a clone so the stored
    map is never mutated (matching the reference tool)."""
    if pool_filter != -1 and pool_filter not in m.pools:
        print(f"There is no pool {pool_filter}", file=sys.stderr)
        raise SystemExit(1)
    if pg_num > 0:
        m = m.clone()
        for pid, pool in m.pools.items():
            if pool_filter != -1 and pid != pool_filter:
                continue
            pool.pg_num = pool.pgp_num = pg_num
            pool.calc_pg_masks()
    n = m.max_osd
    count = np.zeros(n, dtype=np.int64)
    first_count = np.zeros(n, dtype=np.int64)
    primary_count = np.zeros(n, dtype=np.int64)
    size_hist: dict[int, int] = {}

    t0 = time.time()
    mapping = OSDMapMapping()
    mapping.update(m, pool_ids=None if pool_filter == -1
                   else {pool_filter})
    elapsed = time.time() - t0

    total_pgs = 0
    for pid, pool in m.pools.items():
        if pool_filter != -1 and pid != pool_filter:
            continue
        print(f"pool {pid} pg_num {pool.pg_num}")
        pm = mapping.pools[pid]
        total_pgs += pool.pg_num
        acting = pm.acting
        col = np.arange(acting.shape[1])
        valid = (acting != CRUSH_ITEM_NONE) & (acting >= 0) & \
            (col[None, :] < pm.acting_len[:, None])
        vals = acting[valid]
        count += np.bincount(vals, minlength=n)[:n]
        # reference counts the acting vector length incl. NONE holes
        # (osdmaptool.cc:534 size[osds.size()]++)
        sizes = pm.acting_len
        for s, c in zip(*np.unique(sizes, return_counts=True)):
            size_hist[int(s)] = size_hist.get(int(s), 0) + int(c)
        has = valid.any(axis=1)
        firsts = acting[np.arange(len(acting)),
                        np.argmax(valid, axis=1)][has]
        first_count += np.bincount(firsts, minlength=n)[:n]
        prims = pm.acting_primary[pm.acting_primary >= 0]
        primary_count += np.bincount(prims, minlength=n)[:n]
        if dump:
            for ps in range(pool.pg_num):
                osds = [int(o) for o in acting[ps][valid[ps]]]
                print(f"{pid}.{ps:x}\t{osds}\t{pm.acting_primary[ps]}")

    print("#osd\tcount\tfirst\tprimary\tc wt\twt")
    in_osds = [i for i in range(n)
               if m.is_in(i) and m.osd_weight[i] > 0]
    for i in in_osds:
        print(f"osd.{i}\t{count[i]}\t{first_count[i]}\t"
              f"{primary_count[i]}\t1.0\t{m.osd_weight[i] / 0x10000:g}")
    n_in = len(in_osds)
    total = int(count[in_osds].sum()) if in_osds else 0
    avg = total // n_in if n_in else 0
    dev = math.sqrt(sum((avg - int(count[i])) ** 2
                        for i in in_osds) / n_in) if n_in else 0.0
    edev = math.sqrt(total / n_in * (1.0 - 1.0 / n_in)) if n_in else 0.0
    print(f" in {n_in}")
    if avg:
        print(f" avg {avg} stddev {dev:g} ({dev / avg:g}x) "
              f"(expected {edev:g} {edev / avg:g}x))")
    nz = count[in_osds]
    if n_in and nz.any():
        min_i = in_osds[int(np.argmin(np.where(nz > 0, nz, nz.max() + 1)))]
        max_i = in_osds[int(np.argmax(nz))]
        print(f" min osd.{min_i} {count[min_i]}")
        print(f" max osd.{max_i} {count[max_i]}")
    for s in sorted(size_hist):
        print(f"size {s}\t{size_hist[s]}")
    rate = total_pgs / elapsed if elapsed > 0 else float("inf")
    print(f"mapped {total_pgs} pgs in {elapsed:.3f}s "
          f"({rate:,.0f} pg/s)", file=sys.stderr)


def do_upmap(m: OSDMap, out_path: str, deviation: int, max_changes: int,
             pools: list[int]) -> bool:
    """--upmap: run the balancer and write the resulting commands
    (ref: src/tools/osdmaptool.cc:48 usage, :331-404 upmap block).
    Applies the upmaps to the in-memory map (so a --test-map-pgs in the
    same invocation sees the balanced layout) and returns True when
    changes were prepared; the mapfile itself is only rewritten under
    --upmap-save, like the reference tool."""
    from ..osd.balancer import Balancer
    b = Balancer(max_deviation=deviation, max_iterations=max_changes)
    inc = b.optimize(m, pools=pools or None)
    lines = []
    for pg in sorted(inc.old_pg_upmap_items):
        lines.append(f"ceph osd rm-pg-upmap-items {pg}")
    for pg, items in sorted(inc.new_pg_upmap_items.items()):
        pairs = " ".join(f"{frm} {to}" for frm, to in items)
        lines.append(f"ceph osd pg-upmap-items {pg} {pairs}")
    out = open(out_path, "w") if out_path != "-" else sys.stdout
    try:
        for ln in lines:
            print(ln, file=out)
    finally:
        if out is not sys.stdout:
            out.close()
    n = len(lines)
    print(f"osdmaptool: upmap, max-count {max_changes}, "
          f"max deviation {deviation}", file=sys.stderr)
    print(f"prepared {n}/{max_changes} changes", file=sys.stderr)
    if n:
        inc.epoch = m.epoch + 1
        m.apply_incremental(inc)
    return bool(n)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="osdmaptool")
    ap.add_argument("mapfile")
    ap.add_argument("--createsimple", type=int, metavar="N")
    ap.add_argument("--osds-per-host", type=int, default=4)
    ap.add_argument("--pg-num", type=int, default=0)
    ap.add_argument("--pool", type=int, default=-1)
    ap.add_argument("--test-map-pgs", action="store_true")
    ap.add_argument("--test-map-pgs-dump", action="store_true")
    ap.add_argument("--mark-down", type=int, action="append", default=[],
                    metavar="OSD")
    ap.add_argument("--mark-out", type=int, action="append", default=[],
                    metavar="OSD")
    ap.add_argument("--upmap", metavar="FILE",
                    help="calculate pg upmap entries to balance pg layout"
                         " and write the commands to FILE ('-' = stdout)")
    ap.add_argument("--upmap-max", type=int, default=10,
                    help="max upmap entries to calculate")
    ap.add_argument("--upmap-deviation", type=int, default=5,
                    help="max deviation from target pgs per osd")
    ap.add_argument("--upmap-pool", type=int, action="append", default=[],
                    metavar="POOL", help="restrict upmap balancing to pool")
    ap.add_argument("--upmap-save", action="store_true",
                    help="write the upmap results back to the mapfile")
    args = ap.parse_args(argv)

    if args.createsimple:
        m = OSDMap()
        pool = PGPool(pg_num=args.pg_num or max(64, args.createsimple * 4),
                      pgp_num=args.pg_num or max(64, args.createsimple * 4))
        m.build_simple(args.createsimple, pool,
                       osds_per_host=args.osds_per_host)
        save_map(m, args.mapfile)
        print(f"osdmaptool: writing epoch {m.epoch} to {args.mapfile}")
        return 0

    try:
        m = load_map(args.mapfile)
    except FileNotFoundError:
        print(f"osdmaptool: error opening {args.mapfile}: "
              "no such file or directory", file=sys.stderr)
        return 1
    changed = False
    for osd in args.mark_down:
        m.osd_state[osd] &= ~2
        changed = True
    for osd in args.mark_out:
        m.osd_weight[osd] = 0
        changed = True
    if args.upmap:
        did = do_upmap(m, args.upmap, args.upmap_deviation,
                       args.upmap_max, args.upmap_pool)
        changed |= did and args.upmap_save
    if args.test_map_pgs or args.test_map_pgs_dump:
        test_map_pgs(m, args.pool, args.pg_num, args.test_map_pgs_dump)
    if changed:
        save_map(m, args.mapfile)
    return 0


if __name__ == "__main__":
    sys.exit(main())
