"""Prometheus exporter: cluster + per-daemon metrics over HTTP.

The mgr prometheus module analogue (ref: src/pybind/mgr/prometheus/
module.py — health/osd/pool/pg metrics in the Prometheus exposition
text format, scraped at /metrics).  Each scrape pulls fresh state
through the mon command path (`status`, `df`, `osd perf dump`), so the
exporter itself is stateless.
"""
from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_HEALTH_VALUE = {"HEALTH_OK": 0, "HEALTH_WARN": 1, "HEALTH_ERR": 2}


def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


class _Builder:
    def __init__(self):
        self.lines: list[str] = []

    def metric(self, name: str, help_text: str, kind: str = "gauge"):
        self.lines.append(f"# HELP {name} {help_text}")
        self.lines.append(f"# TYPE {name} {kind}")

    def sample(self, name: str, value, labels: dict | None = None):
        lbl = ""
        if labels:
            lbl = "{" + ",".join(f'{k}="{_esc(v)}"'
                                 for k, v in sorted(labels.items())) \
                + "}"
        self.lines.append(f"{name}{lbl} {float(value):g}")

    def histogram(self, name: str, hist: dict,
                  labels: dict | None = None):
        """One labeled series of a histogram family: cumulative
        _bucket samples over the explicit bounds plus +Inf, then _sum
        and _count (the prometheus exposition histogram contract).
        Declare the family once with metric(name, ..., "histogram")
        before the first series."""
        labels = dict(labels or {})
        cum = 0
        for bound, n in zip(hist["bounds"], hist["buckets"]):
            cum += n
            self.sample(f"{name}_bucket", cum,
                        {**labels, "le": f"{bound:g}"})
        cum += hist["buckets"][-1]          # overflow bucket
        self.sample(f"{name}_bucket", cum, {**labels, "le": "+Inf"})
        self.sample(f"{name}_sum", hist["sum"], labels)
        self.sample(f"{name}_count", hist["count"], labels)

    def render(self) -> str:
        return "\n".join(self.lines) + "\n"


class PrometheusExporter:
    """Serve /metrics off a command channel (`mon_command(cmd) ->
    (rc, outs, outb)`): a Rados handle or a Monitor both qualify."""

    def __init__(self, mon_command, host: str = "127.0.0.1",
                 port: int = 0, progress_ls=None, device_ls=None):
        self._cmd = mon_command
        #: optional callable returning the mgr progress module's
        #: event list (ref: the progress metrics the reference's
        #: prometheus module exports)
        self._progress_ls = progress_ls
        #: optional callable returning devicehealth records
        self._device_ls = device_ls
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                try:
                    body = exporter.collect().encode()
                    status = 200
                except Exception as ex:
                    body = f"# collect failed: {ex}\n".encode()
                    status = 500
                self.send_response(status)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="prometheus",
            daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    # -- collection (ref: prometheus/module.py Module.collect) ---------
    def collect(self) -> str:
        b = _Builder()
        rc, _, status = self._cmd({"prefix": "status"})
        if rc != 0:
            raise RuntimeError("status unavailable")
        b.metric("ceph_health_status",
                 "cluster health (0=OK 1=WARN 2=ERR)")
        b.sample("ceph_health_status",
                 _HEALTH_VALUE.get(status["health"]["status"], 2))
        om = status["osdmap"]
        b.metric("ceph_osd_up", "osd up state")
        b.metric("ceph_osd_in", "osd in state")
        b.sample("ceph_osd_up", om["num_up_osds"])
        b.sample("ceph_osd_in", om["num_in_osds"])
        b.metric("ceph_osdmap_epoch", "current osdmap epoch",
                 "counter")
        b.sample("ceph_osdmap_epoch", om["epoch"])
        pm = status["pgmap"]
        b.metric("ceph_pg_total", "total placement groups")
        b.sample("ceph_pg_total", pm["num_pgs"])
        b.metric("ceph_pg_state", "pg count by state")
        for state, n in sorted(pm.get("pgs_by_state", {}).items()):
            b.sample("ceph_pg_state", n, {"state": state})
        b.metric("ceph_cluster_total_bytes", "raw capacity")
        b.sample("ceph_cluster_total_bytes", pm.get("total_kb", 0) * 1024)
        b.metric("ceph_cluster_total_used_bytes", "raw used")
        b.sample("ceph_cluster_total_used_bytes",
                 pm.get("used_kb", 0) * 1024)
        b.metric("ceph_objects", "total objects")
        b.sample("ceph_objects", pm.get("num_objects", 0))

        rc, _, df = self._cmd({"prefix": "df"})
        if rc == 0:
            b.metric("ceph_pool_objects", "objects per pool")
            b.metric("ceph_pool_bytes", "logical bytes per pool")
            # snaptrim observability: physical store bytes (heads +
            # snap clones) vs the pool's logical bytes exposes the
            # deleted-snapshot space leak, and snaptrim_pgs shows the
            # reclaim actually running (ref: the pg-state and
            # pool-stat gauges of mgr/prometheus)
            b.metric("ceph_pool_store_bytes",
                     "physical store bytes per pool incl. snap clones")
            b.metric("ceph_pool_snaptrim_pgs",
                     "pgs per pool in snaptrim/snaptrim_wait/"
                     "snaptrim_error")
            for pool, st in sorted(df.get("pools", {}).items()):
                b.sample("ceph_pool_objects", st["objects"],
                         {"pool": pool})
                b.sample("ceph_pool_bytes", st["bytes"],
                         {"pool": pool})
                b.sample("ceph_pool_store_bytes",
                         st.get("store_bytes", st["bytes"]),
                         {"pool": pool})
                b.sample("ceph_pool_snaptrim_pgs",
                         st.get("snaptrim_pgs", 0), {"pool": pool})

        rc, _, crashes = self._cmd({"prefix": "crash ls"})
        if rc == 0 and isinstance(crashes, list):
            new = sum(1 for c in crashes if not c.get("archived"))
            b.metric("ceph_crash_reports",
                     "daemon crash reports by archive state")
            b.sample("ceph_crash_reports", new, {"status": "new"})
            b.sample("ceph_crash_reports", len(crashes) - new,
                     {"status": "archived"})

        # rgw multisite sync lag: every in-process sync agent
        # self-registers (ceph_tpu.rgw.multisite._AGENTS) so the
        # scrape sees zone replication state without a daemon-graph
        # dependency — lag_entries returning to 0 IS "caught up"
        from ..rgw.multisite import sync_status_all
        rows = sync_status_all()
        if rows:
            b.metric("ceph_rgw_sync_lag_entries",
                     "datalog entries the zone has not yet applied "
                     "from its source zone")
            b.metric("ceph_rgw_sync_behind_shards",
                     "datalog shards with unapplied entries per "
                     "(zone, source)")
            for row in rows:
                lbl = {"zone": row["zone"], "source": row["source"]}
                b.sample("ceph_rgw_sync_lag_entries",
                         row["lag_entries"], lbl)
                b.sample("ceph_rgw_sync_behind_shards",
                         row["behind_shards"], lbl)
        from ..rgw.multisite import sync_apply_hists
        hists = sync_apply_hists()
        if hists:
            b.metric("ceph_rgw_sync_apply_latency_seconds",
                     "cross-zone fetch + apply latency per "
                     "replicated entry (the sync op class)",
                     "histogram")
            for zone, hist in sorted(hists.items()):
                b.histogram("ceph_rgw_sync_apply_latency_seconds",
                            hist, {"zone": zone})

        rc, _, counts = self._cmd({"prefix": "log counts"})
        if rc == 0:
            b.metric("ceph_cluster_log_messages",
                     "cluster log entries by severity", "counter")
            for level, n in sorted((counts or {}).items()):
                b.sample("ceph_cluster_log_messages", n,
                         {"level": level})

        rc, _, perf = self._cmd({"prefix": "osd perf dump"})
        if rc == 0:
            emitted: set[str] = set()
            totals: dict[str, float] = {}
            for daemon, counters in sorted(perf.items()):
                for key, val in sorted(counters.items()):
                    if isinstance(val, dict) and "buckets" in val:
                        # per-op-class latency histograms export as
                        # REAL prometheus histogram families
                        # (_bucket/_sum/_count with cumulative le
                        # labels), one series per daemon
                        name = f"ceph_daemon_{key}_seconds"
                        if name not in emitted:
                            emitted.add(name)
                            b.metric(name,
                                     f"per-daemon latency {key}",
                                     "histogram")
                        b.histogram(name, val, {"daemon": daemon})
                        continue
                    is_avg = isinstance(val, dict)
                    if is_avg:                  # long-run averages
                        val = val.get("avg", 0.0)
                    elif isinstance(val, list):  # histograms
                        continue
                    name = f"ceph_daemon_{key}"
                    if name not in emitted:
                        emitted.add(name)
                        b.metric(name, f"per-daemon counter {key}",
                                 "counter")
                    b.sample(name, val, {"daemon": daemon})
                    if not is_avg:
                        # averages don't sum: a cluster-wide
                        # "sum of averages" is meaningless
                        totals[key] = totals.get(key, 0.0) \
                            + float(val)
            # cluster-wide aggregation across every reporting daemon
            # (ref: the DaemonServer-side counter aggregation)
            for key, val in sorted(totals.items()):
                name = f"ceph_cluster_{key}"
                b.metric(name, f"cluster-wide sum of {key}", "counter")
                b.sample(name, val)

        if self._device_ls is not None:
            b.metric("ceph_device_health",
                     "device health (0=GOOD 1=WARNING 2=FAILING)")
            b.metric("ceph_device_media_errors",
                     "media error count per device", "counter")
            sev = {"GOOD": 0, "WARNING": 1, "FAILING": 2}
            for d in self._device_ls():
                lbl = {"device": d["device"], "daemon": d["daemon"]}
                b.sample("ceph_device_health",
                         sev.get(d["health"], 2), lbl)
                b.sample("ceph_device_media_errors",
                         d["csum_errors"] + d["read_errors"], lbl)
        if self._progress_ls is not None:
            b.metric("ceph_progress_event",
                     "long-running event completion ratio")
            for ev in self._progress_ls():
                b.sample("ceph_progress_event", ev["progress"],
                         {"id": ev["id"], "message": ev["message"]})
        return b.render()
