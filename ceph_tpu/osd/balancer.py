"""Upmap balancer: calc_pg_upmaps + the mgr-style optimization driver.

Port of the reference's PG-distribution optimizer
(ref: src/osd/OSDMap.cc:4360 calc_pg_upmaps, :4301 try_pg_upmap;
driver: src/pybind/mgr/balancer/module.py:897 do_upmap).  The greedy
loop emits/retracts ``pg_upmap_items`` pairs into an Incremental until
every OSD's PG count is within ``max_deviation_ratio`` of its
weight-proportional target.

TPU-first shape: the expensive part of the reference loop — mapping
every PG of every pool to build ``pgs_by_osd`` — collapses into the
vmapped full-cluster tables of ceph_tpu.osd.mapping (one batched CRUSH
dispatch per pool instead of pg_num scalar walks).  The per-iteration
bookkeeping after a candidate change is O(changed pairs), exactly like
the reference's ``temp_pgs_by_osd`` shuffling, so iteration cost is
independent of cluster size.

Determinism: the reference's *aggressive* mode shuffles candidate PGs
with a random_device; we take an explicit seeded generator so balancer
runs are reproducible (pass ``rng=None`` steps in pg order, which the
reference does in non-aggressive mode).
"""
from __future__ import annotations

import random
from dataclasses import dataclass, field

import numpy as np

from ..common.log import dout
from ..crush.remap import get_rule_weight_osd_map, try_remap_rule
from ..crush.types import CRUSH_ITEM_NONE
from .mapping import OSDMapMapping
from .osdmap import Incremental, OSDMap
from .types import PG

# conf defaults (ref: src/common/options.cc osd_calc_pg_upmaps_*)
MAX_STDDEV = 1.0                   # osd_calc_pg_upmaps_max_stddev
LOCAL_FALLBACK_RETRIES = 100       # osd_calc_pg_upmaps_local_fallback_retries


def _build_pgs_by_osd(tmp: OSDMap, pool_ids: list[int],
                      mapping: OSDMapMapping | None = None
                      ) -> tuple[dict[int, set[PG]], int]:
    """pgs_by_osd over the up sets of the given pools, via the batched
    mapping tables (replaces the per-PG pg_to_up_acting_osds loop at
    OSDMap.cc:4377-4387)."""
    if mapping is None or mapping.epoch != tmp.epoch or \
            any(p not in mapping.pools for p in pool_ids):
        mapping = OSDMapMapping()
        mapping.update(tmp, pool_ids=pool_ids)
    pgs_by_osd: dict[int, set[PG]] = {}
    total_pgs = 0
    for pool_id in pool_ids:
        pool = tmp.pools[pool_id]
        total_pgs += pool.size * pool.pg_num
        pm = mapping.pools[pool_id]
        valid = (pm.up != CRUSH_ITEM_NONE) & (pm.up >= 0)
        rows, cols = np.nonzero(valid)
        osds_flat = pm.up[rows, cols]
        # group rows by osd with one stable sort instead of 3M
        # setdefault/add calls (this build was ~90% of a 1M-PG
        # balancer invocation); one PG object per ps, shared across
        # every set that references it
        if len(osds_flat) == 0:
            continue
        order = np.argsort(osds_flat, kind="stable")
        so = osds_flat[order]
        sp = rows[order]
        pg_of = [PG(pool_id, ps) for ps in range(pool.pg_num)]
        cuts = np.nonzero(np.diff(so))[0] + 1
        starts = np.concatenate(([0], cuts))
        ends = np.concatenate((cuts, [len(so)]))
        for s, e in zip(starts.tolist(), ends.tolist()):
            osd = int(so[s])
            seg = {pg_of[ps] for ps in sp[s:e].tolist()}
            ex = pgs_by_osd.get(osd)
            if ex is None:
                pgs_by_osd[osd] = seg
            else:
                ex |= seg
    return pgs_by_osd, total_pgs


def _try_pg_upmap(tmp: OSDMap, pg: PG, overfull: set[int],
                  underfull: list[int], parent: dict[int, int]
                  ) -> tuple[list[int], list[int]] | None:
    """(orig, out) when the rule admits a remap moving pg off an
    overfull osd; None otherwise (ref: OSDMap.cc:4301 try_pg_upmap)."""
    pool = tmp.pools.get(pg.pool)
    if pool is None:
        return None
    ruleno = tmp.crush.find_rule(pool.crush_rule, pool.type, pool.size)
    if ruleno < 0:
        return None
    orig = tmp.pg_to_raw_upmap(pg)
    if not any(o in overfull for o in orig):
        return None
    out = try_remap_rule(tmp.crush, ruleno, pool.size, overfull,
                         underfull, orig, parent)
    if out == orig:
        return None
    return orig, out


@dataclass
class _Change:
    """One candidate balancer step (the reference's to_unmap/to_upmap
    pair).  `temp_pgs_by_osd` is a copy-on-write OVERLAY holding only
    the OSDs this change touches — a full copy of pgs_by_osd per
    candidate is O(total PG replicas) and was the 10s/iteration wall
    at 1M PGs (VERDICT r4 weak #2); a change moves a handful of PGs
    between a handful of OSDs, so scoring only needs those."""
    to_unmap: set[PG] = field(default_factory=set)
    to_upmap: dict[PG, list[tuple[int, int]]] = field(default_factory=dict)
    temp_pgs_by_osd: dict[int, set[PG]] = field(default_factory=dict)

    def found(self) -> bool:
        return bool(self.to_unmap or self.to_upmap)


def calc_pg_upmaps(osdmap: OSDMap, max_deviation_ratio: float,
                   max_iterations: int, only_pools: set[int] | None,
                   pending_inc: Incremental, *,
                   aggressive: bool = True,
                   local_fallback_retries: int = LOCAL_FALLBACK_RETRIES,
                   max_stddev: float = MAX_STDDEV,
                   rng: random.Random | None = None,
                   mapping: OSDMapMapping | None = None) -> int:
    """Emit pg_upmap_items changes into pending_inc until the PG
    distribution is balanced; returns the number of changes
    (ref: src/osd/OSDMap.cc:4360 calc_pg_upmaps)."""
    tmp = osdmap.clone()
    num_changed = 0
    pool_ids = sorted(p for p in tmp.pools
                      if not only_pools or p in only_pools)
    if not pool_ids or max_iterations <= 0:
        return 0

    pgs_by_osd, total_pgs = _build_pgs_by_osd(tmp, pool_ids, mapping)

    # weight-proportional targets (OSDMap.cc:4390-4407)
    osd_weight: dict[int, float] = {}
    for pool_id in pool_ids:
        pool = tmp.pools[pool_id]
        ruleno = tmp.crush.find_rule(pool.crush_rule, pool.type, pool.size)
        if ruleno < 0:
            continue
        for osd, frac in get_rule_weight_osd_map(tmp.crush, ruleno).items():
            adjusted = (tmp.osd_weight[osd] / 0x10000) * frac \
                if 0 <= osd < tmp.max_osd else 0.0
            if adjusted == 0:
                continue
            osd_weight[osd] = osd_weight.get(osd, 0.0) + adjusted
    osd_weight_total = sum(osd_weight.values())
    if osd_weight_total == 0:
        return 0
    for osd in osd_weight:
        pgs_by_osd.setdefault(osd, set())
    # osds outside the rule tree carry no target; drop them from the
    # scoring universe (reference asserts they never appear)
    pgs_by_osd = {o: s for o, s in pgs_by_osd.items() if o in osd_weight}
    pgs_per_weight = total_pgs / osd_weight_total

    decay_factor = 1.0 / max_iterations

    def deviations(counts: dict[int, set[PG]]
                   ) -> tuple[dict[int, float], float]:
        dev = {}
        stddev = 0.0
        for osd, pgs in counts.items():
            # retracting stale upmap pairs can (re)introduce osds with
            # no crush weight (marked-out targets); they carry no
            # target, so they don't participate in scoring
            w = osd_weight.get(osd)
            if w is None:
                continue
            target = w * pgs_per_weight
            d = len(pgs) - target
            dev[osd] = d
            stddev += d * d
        return dev, stddev

    osd_deviation, stddev = deviations(pgs_by_osd)
    if stddev <= max_stddev:
        dout("osd", 10).write("calc_pg_upmaps: distribution is almost perfect")
        return 0

    def sorted_by_dev(dev: dict[int, float]) -> list[tuple[int, float]]:
        return sorted(dev.items(), key=lambda kv: (kv[1], kv[0]))

    from ..crush.remap import build_parent_map
    parent = build_parent_map(tmp.crush)  # crush is immutable in-run

    skip_overfull = False
    it = max_iterations
    while it > 0:
        it -= 1
        by_dev = sorted_by_dev(osd_deviation)
        # overfull/underfull with decaying thresholds (OSDMap.cc:4462)
        overfull: set[int] = set()
        decay_count = 0
        while not overfull:
            decay = decay_factor * decay_count
            overfull = {o for o, d in by_dev if d >= 1.0 - decay}
            if overfull:
                break
            decay_count += 1
            if decay_factor * decay_count >= 1.0:
                break
        if not overfull:
            break
        underfull: list[int] = []
        decay_count = 0
        while not underfull:
            decay = decay_factor * decay_count
            underfull = [o for o, d in by_dev if d < -.999 + decay]
            if underfull:
                break
            decay_count += 1
            if decay_factor * decay_count >= 0.999:
                break
        if not underfull:
            break
        dout("osd", 10).write("calc_pg_upmaps overfull %s underfull %s",
                               sorted(overfull), underfull)

        to_skip: set[PG] = set()
        local_fallback_retried = 0
        outer_continue = False
        while True:  # the reference's `retry:` label
            change = _find_change(
                tmp, pgs_by_osd, osd_deviation, osd_weight, pgs_per_weight,
                by_dev, overfull, underfull, to_skip, skip_overfull,
                max_deviation_ratio, only_pools, aggressive, rng, parent)
            if not change.found():
                if not aggressive:
                    return _finish(num_changed)
                if not skip_overfull:
                    return _finish(num_changed)
                skip_overfull = False
                outer_continue = True
                break
            # test_change: (OSDMap.cc:4763) — incremental rescoring
            # over the overlay's touched OSDs only: stddev' = stddev
            # - Σ d_old² + Σ d_new² (the full-universe recompute is
            # what made each iteration O(cluster size))
            new_stddev = stddev
            temp_dev: dict[int, float] = {}
            for osd, s in change.temp_pgs_by_osd.items():
                w = osd_weight.get(osd)
                if w is None:
                    continue        # weightless: outside scoring
                target = w * pgs_per_weight
                # every weighted OSD is in osd_deviation by
                # construction — fail loudly on a desync rather than
                # silently drifting the incremental stddev
                d_old = osd_deviation[osd]
                d_new = len(s) - target
                new_stddev += d_new * d_new - d_old * d_old
                temp_dev[osd] = d_new
            dout("osd", 10).write("calc_pg_upmaps stddev %s -> %s",
                                      stddev, new_stddev)
            if new_stddev >= stddev:
                if not aggressive:
                    return _finish(num_changed)
                local_fallback_retried += 1
                if local_fallback_retried >= local_fallback_retries:
                    skip_overfull = not skip_overfull
                    outer_continue = True
                    break
                to_skip |= change.to_unmap
                to_skip |= set(change.to_upmap)
                continue  # retry
            # apply: merge the overlay
            stddev = new_stddev
            for osd, s in change.temp_pgs_by_osd.items():
                pgs_by_osd[osd] = s
            osd_deviation.update(temp_dev)
            for pg in change.to_unmap:
                del tmp.pg_upmap_items[pg]
                # a pg can be re-upmapped after an earlier retraction
                # (and vice versa) within one run; the pending inc must
                # hold it in only one of the two collections
                pending_inc.new_pg_upmap_items.pop(pg, None)
                if pg not in pending_inc.old_pg_upmap_items:
                    pending_inc.old_pg_upmap_items.append(pg)
                num_changed += 1
            for pg, items in change.to_upmap.items():
                tmp.pg_upmap_items[pg] = items
                if pg in pending_inc.old_pg_upmap_items:
                    pending_inc.old_pg_upmap_items.remove(pg)
                pending_inc.new_pg_upmap_items[pg] = items
                num_changed += 1
            break
        if outer_continue:
            continue
    return _finish(num_changed)


def _finish(num_changed: int) -> int:
    dout("osd", 10).write("calc_pg_upmaps num_changed = %d", num_changed)
    return num_changed


def _find_change(tmp: OSDMap, pgs_by_osd, osd_deviation, osd_weight,
                 pgs_per_weight, by_dev, overfull, underfull, to_skip,
                 skip_overfull, max_deviation_ratio, only_pools,
                 aggressive, rng, parent) -> _Change:
    """One pass over overfull (descending deviation) then underfull
    osds looking for a single change; mirrors the body between the
    reference's `retry:` and `test_change:` labels (OSDMap.cc:4517)."""
    c = _Change()

    def tset(osd: int) -> set:
        """Copy-on-write: an OSD's PG set enters the overlay the first
        time the change touches it."""
        s = c.temp_pgs_by_osd.get(osd)
        if s is None:
            s = c.temp_pgs_by_osd[osd] = set(pgs_by_osd.get(osd, ()))
        return s

    if not skip_overfull:
        # always start with fullest (OSDMap.cc:4521)
        for osd, deviation in reversed(by_dev):
            target = osd_weight[osd] * pgs_per_weight
            if deviation / target < max_deviation_ratio:
                break
            pgs = [pg for pg in sorted(pgs_by_osd[osd])
                   if pg not in to_skip]
            if aggressive and rng is not None:
                rng.shuffle(pgs)
            # drop existing remappings into this overfull osd first
            for pg in pgs:
                items = tmp.pg_upmap_items.get(pg)
                if items is None:
                    continue
                new_items = []
                for frm, to in items:
                    if to == osd:
                        tset(to).discard(pg)
                        tset(frm).add(pg)
                    else:
                        new_items.append((frm, to))
                if not new_items:
                    c.to_unmap.add(pg)
                    return c
                elif len(new_items) != len(items):
                    c.to_upmap[pg] = new_items
                    return c
            # then try new upmap pairs
            for pg in pgs:
                if pg in tmp.pg_upmap:
                    continue  # admin-specified, leave alone
                pool_size = tmp.pools[pg.pool].size
                new_items = []
                existing: set[int] = set()
                items = tmp.pg_upmap_items.get(pg)
                if items is not None:
                    if len(items) >= pool_size:
                        continue
                    new_items = list(items)
                    for frm, to in items:
                        existing.add(frm)
                        existing.add(to)
                res = _try_pg_upmap(tmp, pg, overfull, underfull, parent)
                if res is None:
                    continue
                orig, out = res
                if len(orig) != len(out):
                    continue
                for i in range(len(out)):
                    if orig[i] == out[i]:
                        continue
                    if orig[i] in existing or out[i] in existing:
                        continue  # new remappings only
                    existing.add(orig[i])
                    existing.add(out[i])
                    tset(orig[i]).discard(pg)
                    tset(out[i]).add(pg)
                    new_items.append((orig[i], out[i]))
                    c.to_upmap[pg] = new_items
                    return c  # append pairs slowly (OSDMap.cc:4654)

    # underfull pass: retract remappings out of underfull osds
    # (OSDMap.cc:4678)
    underfull_set = set(underfull)
    for osd, deviation in by_dev:
        if osd not in underfull_set:
            break
        target = osd_weight[osd] * pgs_per_weight
        if abs(deviation / target) < max_deviation_ratio:
            break
        candidates = [(pg, items)
                      for pg, items in sorted(tmp.pg_upmap_items.items())
                      if pg not in to_skip and
                      (not only_pools or pg.pool in only_pools)]
        if aggressive and rng is not None:
            rng.shuffle(candidates)
        for pg, items in candidates:
            new_items = []
            for frm, to in items:
                if frm == osd:
                    tset(to).discard(pg)
                    tset(frm).add(pg)
                else:
                    new_items.append((frm, to))
            if not new_items:
                c.to_unmap.add(pg)
                return c
            elif len(new_items) != len(items):
                c.to_upmap[pg] = new_items
                return c
    return _Change()  # nothing found


# ---------------------------------------------------------------- driver
class Balancer:
    """mgr balancer (upmap mode) — groups pools by crush rule and
    spends the optimization budget across the groups
    (ref: src/pybind/mgr/balancer/module.py:897 do_upmap)."""

    def __init__(self, max_deviation: int = 5, max_iterations: int = 10,
                 aggressive: bool = True, seed: int | None = 0) -> None:
        self.max_deviation = max_deviation
        self.max_iterations = max_iterations
        self.aggressive = aggressive
        self.seed = seed

    def optimize(self, osdmap: OSDMap,
                 pools: list[int] | None = None) -> Incremental:
        """Build the pending Incremental for one balancer round."""
        inc = Incremental(epoch=osdmap.epoch + 1)
        pool_ids = sorted(pools if pools is not None else osdmap.pools)
        by_rule: dict[int, list[int]] = {}
        for pid in pool_ids:
            pool = osdmap.pools.get(pid)
            if pool is None:
                continue
            by_rule.setdefault(pool.crush_rule, []).append(pid)
        left = self.max_iterations
        rng = random.Random(self.seed) if self.seed is not None else None
        for group in by_rule.values():
            # reference uses a flat per-osd PG-count deviation knob;
            # convert to the ratio calc_pg_upmaps takes, per group
            total_pgs = sum(osdmap.pools[p].size * osdmap.pools[p].pg_num
                            for p in group)
            n_osd = max(1, sum(1 for o in range(osdmap.max_osd)
                               if osdmap.is_in(o)))
            avg = max(1.0, total_pgs / n_osd)
            ratio = self.max_deviation / avg
            did = calc_pg_upmaps(osdmap, ratio, left, set(group), inc,
                                 aggressive=self.aggressive, rng=rng)
            left -= did
            if left <= 0:
                break
        return inc

    def score(self, osdmap: OSDMap,
              mapping: OSDMapMapping | None = None) -> dict:
        """Distribution stats: per-osd PG counts vs targets
        (ref: balancer module.py calc_eval)."""
        pool_ids = sorted(osdmap.pools)
        pgs_by_osd, total_pgs = _build_pgs_by_osd(osdmap, pool_ids, mapping)
        osd_weight: dict[int, float] = {}
        for pid in pool_ids:
            pool = osdmap.pools[pid]
            ruleno = osdmap.crush.find_rule(pool.crush_rule, pool.type,
                                            pool.size)
            if ruleno < 0:
                continue
            for osd, frac in get_rule_weight_osd_map(
                    osdmap.crush, ruleno).items():
                adjusted = (osdmap.osd_weight[osd] / 0x10000) * frac
                if adjusted:
                    osd_weight[osd] = osd_weight.get(osd, 0.0) + adjusted
        wtotal = sum(osd_weight.values())
        if not wtotal:
            return {"stddev": 0.0, "max_deviation": 0.0, "osds": {}}
        ppw = total_pgs / wtotal
        stats = {}
        stddev = 0.0
        max_dev = 0.0
        for osd, w in sorted(osd_weight.items()):
            n = len(pgs_by_osd.get(osd, ()))
            target = w * ppw
            d = n - target
            stats[osd] = {"pgs": n, "target": round(target, 2),
                          "deviation": round(d, 2)}
            stddev += d * d
            max_dev = max(max_dev, abs(d))
        return {"stddev": round(stddev, 2),
                "max_deviation": round(max_dev, 2), "osds": stats}
