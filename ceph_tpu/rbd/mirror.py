"""rbd-mirror: journal-based image replication with failover.

The rbd-mirror model (ref: src/tools/rbd_mirror/ ImageReplayer +
librbd journaling, src/librbd/journal/): a journaled image appends
every mutation to its journal BEFORE applying it (write-ahead, so a
replica replaying the journal converges to the primary's state); a
mirror process registers as a journal client, replays new events onto
the secondary image, commits its position, and trims.

Failover (ref: librbd mirror promote/demote + ImageReplayer's
split-brain handling):

* every image carries a mirror state `{"primary": bool, "epochs":
  [promotion ids]}`; a demoted image refuses local writes;
* **demote/promote** hand primacy over cleanly: promotion appends a
  fresh epoch id, so the promotion CHAIN records the handoff history;
* **force promotion** (primary died) also appends an epoch — when the
  old primary returns, `sync()` compares chains and journal
  positions: a dst that was primary with journal events nobody
  replayed has diverged — **split-brain** — and raises
  `SplitBrainError` until `resync()` rebuilds it from the current
  primary, re-registering at the live journal position.
"""
from __future__ import annotations

import json
import uuid

from ..journal import Journaler, data_obj
from ..client.rados import RadosError
from .image import RBD, Image, RBDError, data_name, header_name


def journal_id(image_name: str) -> str:
    return f"rbd.{image_name}"


def _head_pos(j: Journaler) -> tuple[int, int]:
    """The journal's live (object, offset) head — what a fully-caught-
    up client's commit position equals."""
    _first, active = j._range()
    try:
        size = j.io.stat(data_obj(j.jid, active))["size"]
    except RadosError as ex:
        if ex.errno_name != "ENOENT":
            raise       # an EIO here must NOT read as "caught up"
        size = 0        # head object not written yet: genuinely empty
    return (active, size)


class SplitBrainError(RBDError):
    def __init__(self, msg: str):
        super().__init__(11, f"split-brain: {msg} (resync required)")


# -- mirror image state (ref: librbd::api::Mirror) ----------------------

def _load_meta(ioctx, name: str) -> dict:
    try:
        raw = ioctx.read(header_name(name))
    except RadosError as ex:
        if ex.errno_name != "ENOENT":
            raise       # EIO keeps its errno — only a true miss maps
        raise RBDError(2, f"image {name!r} does not exist") from ex
    try:
        return json.loads(raw.decode())
    except ValueError as ex:
        # a corrupt header is NOT "does not exist": callers that
        # recreate on ENOENT would overwrite a live (damaged) image
        raise RBDError(5, f"image {name!r}: undecodable metadata "
                          f"header") from ex


def _store_meta(ioctx, name: str, meta: dict) -> None:
    ioctx.write_full(header_name(name), json.dumps(meta).encode())


def mirror_state(ioctx, name: str) -> dict | None:
    return _load_meta(ioctx, name).get("mirror")


def mirror_enable(ioctx, name: str) -> None:
    """Mark the image mirrored + primary (journaling required)."""
    meta = _load_meta(ioctx, name)
    if not meta.get("journaling"):
        raise RBDError(22, f"image {name!r} has no journal "
                           "(enable journaling)")
    meta.setdefault("mirror", {"primary": True,
                               "epochs": [uuid.uuid4().hex]})
    _store_meta(ioctx, name, meta)


def demote(ioctx, name: str) -> None:
    """Primary -> non-primary: local writes refuse from here on
    (ref: librbd mirror_image_demote)."""
    meta = _load_meta(ioctx, name)
    m = meta.setdefault("mirror", {"primary": True, "epochs": []})
    m["primary"] = False
    _store_meta(ioctx, name, meta)


def promote(ioctx, name: str, force: bool = False) -> str:
    """Non-primary -> primary with a fresh promotion epoch.  A clean
    promotion requires the local journal fully replayed (every
    registered client caught up); `force` skips that — the disaster
    path whose divergence sync() later detects
    (ref: librbd mirror_image_promote)."""
    meta = _load_meta(ioctx, name)
    m = meta.setdefault("mirror", {"primary": False, "epochs": []})
    if m.get("primary"):
        return m["epochs"][-1] if m["epochs"] else ""
    if not force:
        # clean promotion requires an orderly handoff, provable one of
        # two ways (ref: the demotion-tag ownership check in librbd's
        # promote):
        #  * we are a sync TARGET that replayed from a demoted source
        #    (`src_demoted` recorded by the draining sync); or
        #  * we are the just-demoted image itself (failover abort) and
        #    our OWN journal is fully consumed by every registered
        #    client — nothing of ours can be lost.
        # Residual limit of the single-cluster view: if the remote
        # side was force-promoted AFTER our last sync, this flag is
        # stale — the next sync's split-brain gate catches the
        # divergence, but dual primaries exist until then.
        ok = bool(m.get("src_demoted"))
        if not ok:
            j = Journaler(ioctx, journal_id(name), "promote-check")
            if j.exists():
                clients = j.clients()
                head = _head_pos(j)
                ok = bool(clients) and all(
                    tuple(c.get("pos") or (0, 0)) >= head
                    for c in clients.values())
        if not ok:
            raise RBDError(16, "source not demoted/drained — demote "
                               "the primary and sync first (or force)")
    epoch = uuid.uuid4().hex
    m["primary"] = True
    m.pop("src_demoted", None)
    m.setdefault("epochs", []).append(epoch)
    if force:
        m["force_promoted"] = True
    # a fresh primary journals its own mutations
    if not meta.get("journaling"):
        meta["journaling"] = True
        Journaler(ioctx, journal_id(name), "master").create()
    _store_meta(ioctx, name, meta)
    return epoch


class ImageMirror:
    """Replays one journaled image onto a secondary pool/cluster
    (ref: rbd_mirror ImageReplayer)."""

    def __init__(self, src_ioctx, dst_ioctx, image_name: str,
                 client_id: str = "mirror"):
        self.src = src_ioctx
        self.dst = dst_ioctx
        self.name = image_name
        self.journaler = Journaler(src_ioctx, journal_id(image_name),
                                   client_id)

    def _ensure_dst(self, src_img: Image) -> Image:
        try:
            return Image(self.dst, self.name)
        except RBDError:
            RBD().create(self.dst, self.name, size=src_img.size,
                         order=src_img.order)
            return Image(self.dst, self.name)

    def _check_split_brain(self, src_img: Image, dst: Image) -> None:
        """Divergence gate (ref: ImageReplayer's tag-chain compare):
        the secondary's promotion chain must be a prefix of the
        primary's, AND a secondary that used to be primary must not
        hold journal events nobody ever replayed — those writes exist
        on no other cluster."""
        src_m = src_img.mirror or {}
        dst_m = dst.mirror or {}
        se, de = src_m.get("epochs", []), dst_m.get("epochs", [])
        if de and se[:len(de)] != de:
            raise SplitBrainError(
                f"promotion chains diverged ({de[-1][:8]} vs "
                f"{se[-1][:8] if se else '-'})")
        if len(de) < len(se) and dst.journaling:
            # the dst was primary at epoch de[-1]; if its own journal
            # holds events no client consumed, they were never
            # replicated anywhere — force promotion left them behind
            j = Journaler(self.dst, journal_id(self.name), "sb-check")
            if j.exists():
                head = _head_pos(j)
                consumed = max(
                    (tuple(c.get("pos") or (0, 0))
                     for c in j.clients().values()), default=(0, 0))
                if head > consumed:
                    raise SplitBrainError(
                        "unreplicated events on the demoted image "
                        f"(head {head} > consumed {consumed})")

    def sync(self) -> int:
        """Replay new journal events onto the secondary; returns the
        number of events applied.  Raises SplitBrainError when the
        secondary's history diverged from the primary's."""
        src_img = Image(self.src, self.name)
        try:
            if not src_img.journaling:
                raise RBDError(22, f"image {self.name!r} has no "
                                   "journal (enable journaling)")
            dst = self._ensure_dst(src_img)
            try:
                return self._sync_into(src_img, dst)
            finally:
                # error paths (split-brain, replay failure) must not
                # leak the dst's watch/lock state
                dst.close()
        finally:
            src_img.close()

    def _sync_into(self, src_img: Image, dst: Image) -> int:
        self._check_split_brain(src_img, dst)
        dst._replaying = True          # bypass the non-primary gate
        self.journaler.register_client()
        applied = 0

        def handler(tag, ev):
            nonlocal applied
            applied += 1
            try:
                if tag == "write":
                    dst.write(ev["off"], bytes(ev["data"]))
                elif tag == "discard":
                    dst.discard(ev["off"], ev["len"])
                elif tag == "resize":
                    dst.resize(ev["size"])
                elif tag == "snap_create":
                    dst.snap_create(ev["name"])
                elif tag == "snap_remove":
                    dst.snap_remove(ev["name"])
                elif tag == "snap_rollback":
                    dst.snap_rollback(ev["name"])
                elif tag == "snap_protect":
                    dst.snap_protect(ev["name"])
                elif tag == "snap_unprotect":
                    dst.snap_unprotect(ev["name"])
            except RBDError as ex:
                # replay idempotency: a crash between replay and
                # commit re-delivers entries — EEXIST/ENOENT on
                # snap verbs means the effect already applied
                # (ref: rbd-mirror replay tolerates the same)
                if ex.errno not in (2, 17):
                    raise

        pos = self.journaler.replay(handler)
        dst.flush()
        self.journaler.commit(pos)
        self.journaler.trim()
        # adopt the primary's promotion chain: the secondary's state
        # records every handoff it has replicated through.  A sync
        # that drained a DEMOTED source marks the orderly-handoff
        # gate clean promotion checks.
        if src_img.mirror is not None:
            dmeta = _load_meta(self.dst, self.name)
            dmeta["mirror"] = {
                "primary": False,
                "epochs": list(src_img.mirror.get("epochs", [])),
                "src_demoted":
                    not src_img.mirror.get("primary", True)}
            _store_meta(self.dst, self.name, dmeta)
        return applied

    def resync(self) -> int:
        """Split-brain recovery (ref: rbd mirror image resync +
        ImageReplayer bootstrap): discard the secondary wholesale,
        full-copy the primary's current data, adopt its promotion
        chain as non-primary, and re-register at the LIVE journal
        position so subsequent syncs replay only post-resync events.
        Data-only: the primary's snapshots are not re-created.
        Returns bytes copied."""
        src_img = Image(self.src, self.name)
        try:
            # capture the journal position BEFORE copying: events
            # appended during the copy must replay afterwards (at
            # worst redundantly), never be skipped
            self.journaler.register_client()
            resume_pos = _head_pos(self.journaler)
            # destroy the local copy (its divergent history included)
            try:
                old = Image(self.dst, self.name)
            except RBDError:
                old = None              # nothing local: plain bootstrap
            if old is not None:
                if old.mirror is not None and \
                        old.mirror.get("primary", False):
                    old.close()
                    # the reference's resync refuses on a primary the
                    # same way: inverted direction would wholesale
                    # destroy the image holding the acked writes
                    raise RBDError(
                        16, "refusing to resync a PRIMARY image — "
                            "reverse the mirror direction")
                span = old._object_span()
                snap_ids = [s["id"] for s in old.snaps.values()]
                old.close()
                for objno in range(span):
                    try:
                        self.dst.remove(data_name(self.name, objno))
                    except RadosError:
                        pass    # best-effort: object may not exist
                j = Journaler(self.dst, journal_id(self.name), "rs")
                if j.exists():
                    j.remove()
                # stale object maps would mark objects the rebuilt
                # image does not have (phantom du/fast-diff extents)
                from .image import object_map_name
                for om in ([object_map_name(self.name)] +
                           [object_map_name(self.name, s)
                            for s in snap_ids]):
                    try:
                        self.dst.remove(om)
                    except RadosError:
                        pass    # best-effort: map may not exist
                try:
                    self.dst.remove(header_name(self.name))
                except RadosError:
                    pass        # best-effort: header may not exist
            RBD().create(self.dst, self.name, size=src_img.size,
                         order=src_img.order)
            dst = Image(self.dst, self.name)
            dst._replaying = True
            copied = 0
            step = 1 << src_img.order
            off = 0
            while off < src_img.size:
                n = min(step, src_img.size - off)
                buf = src_img.read(off, n)
                if any(buf):
                    dst.write(off, buf)
                    copied += n
                off += n
            dst.flush()
            dst.close()
            dmeta = _load_meta(self.dst, self.name)
            dmeta["mirror"] = {
                "primary": False,
                "epochs": list((src_img.mirror or {})
                               .get("epochs", []))}
            _store_meta(self.dst, self.name, dmeta)
            # resume FROM the pre-copy journal position: events that
            # landed mid-copy replay on the next sync
            self.journaler.commit(resume_pos)
            return copied
        finally:
            src_img.close()
