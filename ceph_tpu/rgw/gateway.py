"""S3-flavored HTTP gateway over RADOS.

The radosgw analogue (ref: src/rgw/rgw_main.cc REST frontend;
src/rgw/rgw_rados.cc data layout).  Faithful structure, reduced
surface:

* **Bucket index is omap** on a per-bucket index object — exactly the
  reference's layout (ref: src/cls/rgw bucket index objects; here the
  index is maintained with plain omap ops instead of the cls_rgw
  transaction dance).
* **Object data** lives in RADOS objects named `<bucket>/<key>`;
  multipart parts are separate RADOS objects assembled on complete
  (ref: rgw multipart: RGWCompleteMultipart assembles the manifest —
  here parts are concatenated since striping policy is the Striper's
  job).
* **Bucket index is SHARDED**: keys hash across N index shard objects
  (ref: rgw bucket index shards, rgw_rados bucket_index_max_shards /
  rgw_shard_id — the single-object index was the exact bottleneck the
  reference's sharding removes); listings merge the shards.
* REST: ListBuckets / Create/Delete/HeadBucket, Put/Get/Head/Delete
  Object, CopyObject (x-amz-copy-source), ListObjectsV2 (prefix +
  max-keys + continuation), multipart initiate/upload-part/complete/
  abort.  XML shapes follow S3 close enough for scripted clients.
* **Object versioning** (ref: rgw versioned buckets): per-bucket
  Enabled/Suspended state; versioned PUTs stack version records on
  the index entry with data at `<bucket>/<key>@<vid>`; DELETE inserts
  a delete marker; GET/HEAD honor `versionId`; GET `?versions` lists
  the stack; the pre-versioning object becomes the S3 "null" version.
* **Bucket lifecycle** (ref: src/rgw/rgw_lc.cc): Put/Get/Delete
  lifecycle configuration (Expiration.Days +
  NoncurrentVersionExpiration.NoncurrentDays per prefix rule);
  `lc_tick()` applies expirations — delete markers for current
  versions, outright removal for noncurrent ones.

**Auth**: with a keyring, every request must carry a valid AWS SigV4
signature whose access key is a cephx entity (ref: src/rgw/
rgw_auth_s3.cc) — either the Authorization header or the query-string
presigned-URL form (X-Amz-Signature, ref: rgw_auth_s3.h); without a
keyring the gateway is anonymous (test mode).  With `keystone_url`
set, S3 requests may instead carry an OpenStack token in
`X-Auth-Token`, validated against the keystone endpoint (ref:
rgw_auth_keystone.cc; config-gated the same way).

**Multisite** (ref: src/rgw/rgw_data_sync.cc; model in
rgw/multisite.py): a gateway constructed with `zone=` becomes a zone
member — every index mutation also appends a datalog record in the
same OSD transaction, a `SyncAgent` thread pulls peer zones' datalogs
and applies them idempotently, `/admin/*` REST ops expose the period,
bucket index dumps, datalog cursors and sync status, and replicated
writes carry an `x-rgw-zone-trace` so they neither loop nor re-fire
bucket notifications.
"""
from __future__ import annotations

import hashlib
import itertools
import json
import threading

from ..common.lockdep import make_lock
from ..common.log import dout
import time
import urllib.error
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, quote, unquote, urlparse
from xml.etree import ElementTree as ET
from xml.sax.saxutils import escape

from ..client import RadosError, WriteOp
from .auth import (KeystoneEngine, KeystoneError, SigV4Error,
                   sign_request,
                   verify as sigv4_verify,
                   verify_presigned as presigned_verify)
from ..cls.rgw import (DL_META, DL_PREFIX, is_tomb, now_str,
                       parse_mtime)
from .datalog import DataLog, is_dl_key, shard_obj, shard_of_key
from .notify import (EventPusher, TopicStore, ZONE_TRACE_HEADER,
                     _queue_obj, event_matches, format_zone_trace,
                     make_event, notification_xml,
                     parse_notification_xml, parse_zone_trace,
                     suppress_for_trace)
from .sts import AKID_PREFIX, STSEngine, STSError

#: omap object holding the bucket registry (name -> creation meta)
BUCKETS_OBJ = ".rgw.buckets.list"
#: index shards per bucket (ref: rgw_override_bucket_index_max_shards)
DEFAULT_INDEX_SHARDS = 8


_shard_of = shard_of_key


def _index_obj(bucket: str, shard: int = 0) -> str:
    return shard_obj(bucket, shard)


def _data_obj(bucket: str, key: str) -> str:
    return f"{bucket}/{key}"


class S3Error(Exception):
    def __init__(self, status: int, code: str, msg: str = ""):
        self.status = status
        self.code = code
        self.msg = msg or code
        super().__init__(code)


class RGWGateway:
    """One gateway instance bound to an HTTP port, backed by a pool."""

    def __init__(self, rados, pool: str = "rgw",
                 host: str = "127.0.0.1", port: int = 0,
                 keyring=None, index_shards: int = DEFAULT_INDEX_SHARDS,
                 zone: str | None = None, sync_interval: float = 0.1,
                 system_key: tuple[str, str] | None = None,
                 keystone_url: str | None = None):
        self.rados = rados
        self.pool = pool
        #: cephx keyring doubling as the S3 credential store
        #: (ref: radosgw users in the cluster auth database); None =
        #: anonymous gateway
        self.keyring = keyring
        self.index_shards = index_shards
        #: per-request zone trace (the parsed x-rgw-zone-trace header,
        #: one slot per handler thread): which zones this mutation has
        #: already applied at — drives datalog trace extension, the
        #: notification guard, and forward-loop suppression
        self._reqctx = threading.local()
        #: multisite identity: the zone this gateway serves (None =
        #: standalone gateway, no datalog, no sync agent)
        self.zone = zone
        #: optional FaultPlane; peer_request consults it so partition
        #: rules cover the HTTP sync path as well as the messenger
        self.faults = None
        #: (access_key, secret) this gateway signs sync/forwarded
        #: requests to peers with (ref: the multisite system user)
        self.system_key = system_key
        #: config-gated keystone token validation (satellite of the
        #: multisite PR; ref: rgw_auth_keystone.cc)
        self.keystone = KeystoneEngine(keystone_url) \
            if keystone_url else None
        try:
            rados.pool_lookup(pool)
        except RadosError:
            rados.pool_create(pool, pg_num=32)
        self.io = rados.open_ioctx(pool)
        try:
            self.io.create(BUCKETS_OBJ)
        except RadosError:
            pass
        # op tracking + span ring (ref: rgw's req tracking behind
        # `radosgw-admin ... ops` + the rgw blkin trace roots): every
        # HTTP request is tracked; traced ones root a span the
        # objecter legs nest under (gateway -> objecter -> OSD ->
        # shards in one assembled tree)
        from ..common.options import global_config as _gc
        from ..common.tracked_op import OpTracker
        from ..common.tracing import Tracer
        self.op_tracker = OpTracker(
            history_size=_gc()["osd_op_history_size"])
        self.tracer = Tracer(f"rgw.{zone or pool}")
        self.asok = None
        self._req_ids = itertools.count(1)
        gw = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):      # quiet
                pass

            def _run(self, method):
                from ..common.tracing import new_trace, trace_scope
                opkey = (threading.get_ident(), next(gw._req_ids))
                gw.op_tracker.start(
                    opkey, f"http_req({method} {self.path})")
                ctx = new_trace() \
                    if _gc()["blkin_trace_all"] else None
                sp = gw.tracer.start_span(
                    ctx, f"rgw_op:{method} {self.path.split('?')[0]}")
                try:
                    with trace_scope(ctx):
                        self._run_inner(method)
                finally:
                    gw.op_tracker.finish(opkey)
                    gw.tracer.finish(sp)

            def _run_inner(self, method):
                try:
                    body = gw._read_body(self)
                    self._body = body
                    u = urlparse(self.path)
                    if u.path == "/auth/v1.0" or \
                            u.path == "/swift/v1" or \
                            u.path.startswith("/swift/v1/"):
                        # Swift speaks TempAuth tokens, not SigV4.
                        # The boundary matters: bucket "swift" with
                        # key "v1.txt" is an S3 path, not Swift.
                        return gw._run_swift(self, method, u)
                    ks_token = self.headers.get("x-auth-token")
                    authz = self.headers.get("Authorization") or ""
                    if gw.keystone is not None and \
                            gw.keyring is None and not ks_token and \
                            gw.system_key is not None and \
                            f"Credential={gw.system_key[0]}/" in authz:
                        # peer sync/forward traffic signs SigV4 as the
                        # multisite system user and has no token to
                        # offer: a keystone-only zone member must
                        # verify that signature, not fail it closed —
                        # or the zone never receives sync traffic
                        try:
                            self.s3_user = sigv4_verify(
                                method, self.path, self.headers, body,
                                lambda n, _k=gw.system_key:
                                    _k[1] if n == _k[0] else None)
                        except SigV4Error as e:
                            raise S3Error(403, e.code, str(e))
                    elif gw.keystone is not None and \
                            (ks_token or gw.keyring is None):
                        # keystone path: token present, or tokens are
                        # the ONLY configured auth — a missing token
                        # then fails closed (config-gated: gateways
                        # without keystone_url never take this branch)
                        try:
                            self.s3_user = gw.keystone.validate(
                                ks_token or "")
                        except KeystoneError as e:
                            raise S3Error(e.status, e.code, e.msg)
                    elif gw.keyring is not None:
                        def lookup(name, _h=self.headers):
                            # STS-prefixed access keys resolve their
                            # signing secret from the temp-credential
                            # table (session token required), not the
                            # cephx keyring (ref: rgw_auth_s3.cc
                            # STSAuthStrategy)
                            if name.startswith(AKID_PREFIX):
                                return gw.sts.resolve_secret(
                                    name, _h.get(
                                        "x-amz-security-token", ""))
                            return gw.keyring.get(name)
                        try:
                            presigned = "X-Amz-Signature" in parse_qs(
                                urlparse(self.path).query)
                            if presigned:
                                # query-string auth: presigned URL
                                self.s3_user = presigned_verify(
                                    method, self.path, self.headers,
                                    lookup)
                            else:
                                self.s3_user = sigv4_verify(
                                    method, self.path, self.headers,
                                    body, lookup)
                        except SigV4Error as e:
                            raise S3Error(403, e.code, str(e))
                        except STSError as e:
                            raise S3Error(e.status, e.code, e.msg)
                    raw_trace = self.headers.get(ZONE_TRACE_HEADER, "")
                    if raw_trace and (gw.keyring is not None or
                                      gw.keystone is not None) and \
                            (gw.system_key is None or
                             getattr(self, "s3_user", None) !=
                             gw.system_key[0]):
                        # only the multisite system user speaks for
                        # other zones on a secured gateway: a client
                        # spoofing the trace would suppress its own
                        # write's replication + notifications
                        raw_trace = ""
                    gw._reqctx.trace = parse_zone_trace(raw_trace)
                    try:
                        gw._route(self, method)
                    finally:
                        gw._reqctx.trace = []
                except S3Error as e:
                    body = (f'<?xml version="1.0"?><Error><Code>'
                            f"{e.code}</Code><Message>{escape(e.msg)}"
                            f"</Message></Error>").encode()
                    self.send_response(e.status)
                    self.send_header("Content-Type", "application/xml")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (RadosError, OSError) as e:
                    body = str(e).encode()
                    self.send_response(500)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

            def do_GET(self):
                self._run("GET")

            def do_PUT(self):
                self._run("PUT")

            def do_POST(self):
                self._run("POST")

            def do_DELETE(self):
                self._run("DELETE")

            def do_HEAD(self):
                self._run("HEAD")

        class Server(ThreadingHTTPServer):
            # join handler threads on close (ThreadingHTTPServer
            # defaults daemon_threads=True): the final GC sweep in
            # shutdown() must observe every in-flight request's
            # deferred deletions
            daemon_threads = False

        self.httpd = Server((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None
        self.topics = TopicStore(self.io)
        self.pusher = EventPusher(self.io, self.topics)
        self.sts = STSEngine(self.io)
        self.datalog = DataLog(self.io)
        #: period view + sync agent, only for zone members
        self.multisite = None
        self.sync = None
        if zone is not None:
            from .multisite import MultisiteState, SyncAgent
            self.multisite = MultisiteState(self.io, zone)
            self.sync = SyncAgent(self, interval=sync_interval)
        from .swift import SwiftFrontend
        self.swift = SwiftFrontend(self)
        #: deferred GC of data objects orphaned by index commits —
        #: a reader that resolved the OLD index entry gets a grace
        #: window to finish its data read (the reference defers the
        #: same way via rgw gc; immediate deletion 500'd racing GETs)
        self._gc_queue: list[tuple[float, str]] = []
        self._gc_lock = make_lock("rgw.gc")
        self._gc_stop = threading.Event()
        #: serializes in-process registry mutations: a tombstone
        #: prune's read-then-remove racing a handler thread's bucket
        #: recreate must not remove the fresh live entry
        self._registry_lock = make_lock("rgw.registry")
        #: bucket -> (monotonic stamp, {shard: datalog head}) at the
        #: most recent index dump served to a peer: an in-flight
        #: full sync's incremental cursor starts AT that head, so
        #: age-based trim (zero-peer zones) must not cross it while
        #: the grace window is open
        self._fullsync_floors: dict[str, tuple[float, dict]] = {}
        self._fullsync_lock = make_lock("rgw.fullsync_floors")

    #: seconds an orphaned object outlives its index unlink
    GC_GRACE_S = 2.0
    #: how long a served index dump pins the datalog against
    #: age-based trim (a full sync slower than this restarts from a
    #: fresh dump anyway)
    FULLSYNC_GRACE_S = 600.0

    def note_fullsync_dump(self, bucket: str) -> None:
        """Record the per-shard datalog heads at the moment a bucket
        index dump leaves for a peer (the full-sync entry point)."""
        heads = self.datalog.heads(bucket, self._nshards(bucket))
        import time as _time
        with self._fullsync_lock:
            self._fullsync_floors[bucket] = (_time.monotonic(), heads)

    def fullsync_floor(self, bucket: str) -> dict | None:
        """{shard: head-at-dump} for an in-flight (non-expired) full
        sync of `bucket`, else None."""
        import time as _time
        with self._fullsync_lock:
            rec = self._fullsync_floors.get(bucket)
            if rec is None:
                return None
            stamp, heads = rec
            if _time.monotonic() - stamp > self.FULLSYNC_GRACE_S:
                del self._fullsync_floors[bucket]
                return None
            return dict(heads)

    def _gc_loop(self) -> None:
        while not self._gc_stop.is_set():
            self._gc_tick()
            self._gc_stop.wait(0.25)

    def _gc_tick(self, everything: bool = False) -> int:
        now = time.time()
        with self._gc_lock:
            due = [o for t, o in self._gc_queue
                   if everything or t <= now]
            self._gc_queue = [(t, o) for t, o in self._gc_queue
                              if not everything and t > now]
        for obj in due:
            try:
                self.io.remove(obj)
            except RadosError:
                pass
        return len(due)

    def _run_swift(self, h, method: str, u) -> None:
        """Swift protocol branch (ref: rgw_rest_swift.cc — one
        radosgw process serves both APIs over one bucket namespace)."""
        from .swift import SwiftError
        q = {k: v[0] for k, v in parse_qs(
            u.query, keep_blank_values=True).items()}
        try:
            if u.path == "/auth/v1.0":
                return self.swift.handle_auth(h)
            return self.swift.route(h, method, unquote(u.path), q)
        except SwiftError as e:
            body = (e.msg or "").encode()
            h.send_response(e.status)
            h.send_header("Content-Type", "text/plain")
            h.send_header("Content-Length", str(len(body)))
            h.end_headers()
            if h.command != "HEAD":
                h.wfile.write(body)

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="rgw", daemon=True)
        self._thread.start()
        self.pusher.start()
        threading.Thread(target=self._gc_loop, name="rgw-gc",
                         daemon=True).start()
        if self.sync is not None:
            self.sync.start()

    def shutdown(self) -> None:
        if self.sync is not None:
            # agent first: its in-flight batch is abandoned before the
            # marker persists — the restart replays it (idempotent)
            self.sync.stop()
        if self.asok is not None:
            self.asok.shutdown()
            self.asok = None
        self.pusher.stop()
        self._gc_stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()
        # no requests can race us anymore: collect everything pending
        self._gc_tick(everything=True)

    def start_admin_socket(self, path: str) -> None:
        """`ceph daemon rgw.<zone> <cmd>` endpoint — the same
        op-tracker/trace surface every other daemon serves."""
        from ..common.admin_socket import AdminSocket
        from ..common.obs import register_obs_commands
        a = AdminSocket(path)
        register_obs_commands(a, self.op_tracker, self.tracer)
        a.register("status", "gateway status",
                   lambda c: (0, {"zone": self.zone, "pool": self.pool,
                                  "port": self.port,
                                  "hbmap_unhealthy":
                                      (self.sync.hbmap
                                       .get_unhealthy_workers()
                                       if getattr(self, "sync", None)
                                       is not None else [])}))
        a.start()
        self.asok = a

    def prune_registry_tombstones(self, peer_views: dict) -> int:
        """Drop bucket-deletion tombstones every peer has confirmed
        past (ref: the reference trims metadata logs by the minimum
        peer marker).  `peer_views` maps source zone -> (fetch stamp,
        that zone's raw registry dump) from THIS round.  A tombstone
        may go once, for every peer, the view POSTDATES the deletion
        (a snapshot taken before it proves nothing — a bucket deleted
        mid-round would be pruned off stale absence evidence) and the
        peer either (a) carries the same deletion (its sync applied
        it), (b) has no entry at all (never replicated the bucket, or
        already pruned its own tombstone), or (c) recreated the
        bucket after the deletion — a peer still holding a LIVE
        pre-deletion copy keeps the tombstone, since our next listing
        pull would resurrect the bucket without it.  Returns the
        number pruned; bounded registry growth is the point."""
        candidates: dict[str, str] = {}
        for bucket, meta in self._buckets_raw().items():
            if "deleted" not in meta:
                continue
            dt = meta["deleted"]
            ok = True
            for stamp, view in peer_views.values():
                if stamp <= dt:
                    ok = False      # evidence predates the deletion
                    break
                ent = view.get(bucket)
                if ent is None:
                    continue                       # (b)
                if "deleted" in ent and ent["deleted"] >= dt:
                    continue                       # (a)
                if ent.get("created", "") > dt:
                    continue                       # (c)
                ok = False
                break
            if ok:
                candidates[bucket] = dt
        if not candidates:
            return 0
        with self._registry_lock:
            # ONE locked re-read covering every candidate: a handler
            # thread may have recreated a bucket since the snapshot —
            # removing its key then would delete the LIVE entry
            cur = self._buckets_raw()
            drop = [b for b, dt in candidates.items()
                    if cur.get(b, {}).get("deleted") == dt]
            if drop:
                self.io.remove_omap_keys(BUCKETS_OBJ, drop)
        for b in drop:
            dout("rgw", 4).write(
                "%s: pruned tombstone for bucket %r (deleted %s, "
                "all %d peers past it)", self.zone, b, candidates[b],
                len(peer_views))
        return len(drop)

    # -- notifications (ref: src/rgw/rgw_pubsub.cc) ----------------------
    def _notify_event(self, bucket: str, key: str, event: str,
                      size: int, etag: str, vid: str | None = None,
                      bmeta: dict | None = None,
                      trace: list | None = None) -> None:
        """Publish an event to every topic whose bucket config
        matches.  The append goes through cls queue.enqueue so the
        OSD assigns the sequence — concurrent gateways publishing to
        one topic keep a single total order (ref: rgw_notify.cc
        persistent notifications over cls_2pc_queue).

        A write carrying a zone trace was replicated here (sync apply
        or a forwarded metadata op): the origin zone already notified,
        so the replica must NOT re-fire (ref: rgw_notify.cc skipping
        system requests) — the x-rgw-zone-trace-aware guard."""
        if trace is None:
            trace = self._request_trace()
        if suppress_for_trace(trace):
            return
        if bmeta is None:
            bmeta = self._buckets().get(bucket) or {}
        cfgs = bmeta.get("notifications") or []
        for cfg in cfgs:
            if not event_matches(cfg, event, key):
                continue
            t = self.topics.get(cfg["topic"])
            if not t or not t.get("endpoint"):
                # nothing will ever drain an endpointless topic's
                # queue — don't grow it without bound
                continue
            data = make_event(bucket, key, event, size, etag, vid)
            try:
                self.io.exec(_queue_obj(cfg["topic"]), "queue",
                             "enqueue", {"entries": [data]})
            except RadosError:
                pass            # lost event beats failed client op

    # -- helpers ---------------------------------------------------------
    def _request_trace(self) -> list[str]:
        """Zones the current request's mutation has already applied at
        ([] outside a handler thread / on a direct client write)."""
        return list(getattr(self._reqctx, "trace", ()) or ())

    def _buckets_raw(self) -> dict[str, dict]:
        """Registry incl. deletion tombstones ({"deleted": mtime}) —
        the sync surface.  Client-facing paths use _buckets()."""
        vals, _ = self.io.get_omap_vals(BUCKETS_OBJ)
        return {k: json.loads(v) for k, v in vals.items()}

    def _buckets(self) -> dict[str, dict]:
        return {k: v for k, v in self._buckets_raw().items()
                if "deleted" not in v}

    def _require_bucket(self, bucket: str) -> dict:
        b = self._buckets().get(bucket)
        if b is None:
            raise S3Error(404, "NoSuchBucket", bucket)
        return b

    def _nshards(self, bucket: str) -> int:
        b = self._buckets().get(bucket) or {}
        return int(b.get("shards", 1))

    def _index(self, bucket: str) -> dict[str, dict]:
        """Merged view across every index shard (listings; ref: the
        reference's sharded bucket listing merge, CLSRGWIssueBucketList
        over shards)."""
        out: dict[str, dict] = {}
        for shard in range(self._nshards(bucket)):
            try:
                vals, _ = self.io.get_omap_vals(
                    _index_obj(bucket, shard))
            except RadosError:
                continue
            for k, v in vals.items():
                if is_dl_key(k):
                    continue    # datalog records share the omap but
                    # are not index entries (multisite change feed)
                ent = json.loads(v)
                if is_tomb(ent):
                    continue    # per-key delete tombstone: the key is
                    # gone as far as reads/listings are concerned
                out[k] = ent
        return out

    def _index_entry(self, bucket: str, key: str,
                     nshards: int | None = None) -> dict | None:
        if nshards is None:
            nshards = self._nshards(bucket)
        shard = _shard_of(key, nshards)
        try:
            vals = self.io.get_omap_vals_by_keys(
                _index_obj(bucket, shard), [key])
        except RadosError as e:
            if e.errno_name == "ENOENT":
                return None     # shard object never written: the key
                # cannot have an entry (same contract as _index)
            raise
        ent = json.loads(vals[key]) if key in vals else None
        return None if is_tomb(ent) else ent

    @staticmethod
    def _respond(h, status: int, body: bytes = b"",
                 ctype: str = "application/xml",
                 headers: dict | None = None) -> None:
        h.send_response(status)
        h.send_header("Content-Type", ctype)
        hdrs = dict(headers or {})
        # HEAD replies advertise the real object size with no body
        # (RFC 9110 §8.6 allows Content-Length without payload)
        h.send_header("Content-Length",
                      hdrs.pop("Content-Length", str(len(body))))
        for k, v in hdrs.items():
            h.send_header(k, v)
        h.end_headers()
        if h.command != "HEAD":
            h.wfile.write(body)

    @staticmethod
    def _read_body(h) -> bytes:
        if hasattr(h, "_body"):      # cached by the auth gate
            return h._body
        n = int(h.headers.get("Content-Length", 0))
        return h.rfile.read(n) if n else b""

    # -- routing ---------------------------------------------------------
    def _route(self, h, method: str) -> None:
        u = urlparse(h.path)
        q = {k: v[0] for k, v in parse_qs(u.query,
                                          keep_blank_values=True).items()}
        parts = unquote(u.path).lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        if bucket == "admin":
            # reserved admin/sync surface (ref: rgw's /admin REST
            # resources — the bucket namespace likewise loses the
            # name to the control plane)
            return self._admin_op(h, method, key, q)
        if not bucket:
            if q.get("Action") in ("AssumeRole", "CreateRole",
                                   "DeleteRole", "ListRoles"):
                return self._sts_op(h, method, q)
            if "Action" in q:
                return self._topic_op(h, method, q)
            if method != "GET":
                raise S3Error(405, "MethodNotAllowed")
            return self._list_buckets(h)
        if not key:
            return self._bucket_op(h, method, bucket, q)
        return self._object_op(h, method, bucket, key, q)

    # -- service level ---------------------------------------------------
    def _list_buckets(self, h) -> None:
        ents = "".join(
            f"<Bucket><Name>{escape(b)}</Name><CreationDate>"
            f"{m['created']}</CreationDate></Bucket>"
            for b, m in sorted(self._buckets().items()))
        self._respond(h, 200, (
            '<?xml version="1.0"?><ListAllMyBucketsResult>'
            f"<Buckets>{ents}</Buckets>"
            "</ListAllMyBucketsResult>").encode())

    def _update_bucket_meta(self, bucket: str, meta: dict) -> None:
        self.io.operate(BUCKETS_OBJ, WriteOp().set_omap(
            {bucket: json.dumps(meta).encode()}))

    # -- bucket level ----------------------------------------------------
    def _bucket_op(self, h, method: str, bucket: str, q: dict) -> None:
        if "versioning" in q:
            return self._versioning_op(h, method, bucket)
        if "lifecycle" in q:
            return self._lifecycle_op(h, method, bucket)
        if "notification" in q:
            return self._notification_op(h, method, bucket)
        if method == "PUT":
            fwd = self._forward_to_master(h, "PUT", f"/{quote(bucket)}")
            # adopt the master's created stamp: per-zone stamps would
            # make the generation guards (sync_reset_bucket) unable to
            # recognize the SAME incarnation across zones
            self._create_bucket(
                bucket,
                created=fwd[1].get("x-rgw-created") if fwd else None)
            return self._respond(h, 200, headers={
                "Location": f"/{bucket}",
                "x-rgw-created":
                    self._buckets_raw().get(bucket, {})
                        .get("created", "")})
        self._require_bucket(bucket)
        if method in ("GET", "HEAD"):
            if method == "HEAD":
                return self._respond(h, 200)
            if "versions" in q:
                return self._list_versions(h, bucket, q)
            return self._list_objects(h, bucket, q)
        if method == "DELETE":
            if self._index(bucket):
                raise S3Error(409, "BucketNotEmpty", bucket)
            # master first: once it drops the bucket from its
            # registry, sync stops resurrecting it here (zones beyond
            # the two involved keep theirs — deletion propagation to
            # third zones is an open follow-up)
            self._forward_to_master(h, "DELETE", f"/{quote(bucket)}")
            self._delete_bucket(bucket)
            return self._respond(h, 204)
        raise S3Error(405, "MethodNotAllowed", method)

    def _create_bucket(self, bucket: str,
                       created: str | None = None) -> bool:
        """Shared by the S3 and Swift frontends — ONE place defines
        bucket meta and index layout.  Returns False when the bucket
        already existed (idempotent re-create must NOT rebuild the
        meta: that would silently wipe versioning/lifecycle state).
        `created` adopts the metadata master's stamp on a forwarded
        create — every zone must agree on the incarnation stamp."""
        with self._registry_lock:
            if bucket in self._buckets():
                return False
            meta = json.dumps({"created": created or self._now_str(),
                               "shards": self.index_shards}).encode()
            self.io.operate(BUCKETS_OBJ,
                            WriteOp().set_omap({bucket: meta}))
        for shard in range(self.index_shards):
            self.io.create(_index_obj(bucket, shard))
        return True

    def _delete_bucket(self, bucket: str,
                       deleted_at: str | None = None,
                       tombstone: bool = True) -> None:
        """Emptiness is the caller's check (protocols differ on the
        error shape).  Zone members leave a registry tombstone (the
        origin's deletion time, so created-vs-deleted comparisons
        propagate) — removing the key outright made any peer's next
        listing resurrect the bucket.  tombstone=False drops the key
        anyway (sync_reset_bucket: the new incarnation's created
        stamp predates any deletion time we could write)."""
        nshards = self._nshards(bucket)
        if self.zone is not None and tombstone:
            self.io.operate(BUCKETS_OBJ, WriteOp().set_omap(
                {bucket: json.dumps(
                    {"deleted": deleted_at or self._now_str()}
                ).encode()}))
        else:
            self.io.remove_omap_keys(BUCKETS_OBJ, [bucket])
        for shard in range(nshards):
            try:
                self.io.remove(_index_obj(bucket, shard))
            except RadosError:
                pass

    # -- versioning (ref: rgw versioned buckets; S3 PutBucketVersioning)
    def _versioning_op(self, h, method: str, bucket: str) -> None:
        meta = self._require_bucket(bucket)
        if method == "GET":
            status = meta.get("versioning", "")
            inner = f"<Status>{status}</Status>" if status else ""
            return self._respond(h, 200, (
                '<?xml version="1.0"?><VersioningConfiguration>'
                f"{inner}</VersioningConfiguration>").encode())
        if method != "PUT":
            raise S3Error(405, "MethodNotAllowed", method)
        try:
            root = ET.fromstring(self._read_body(h))
            status = next((el.text for el in root.iter()
                           if el.tag.endswith("Status")), None)
        except ET.ParseError:
            raise S3Error(400, "MalformedXML")
        if status not in ("Enabled", "Suspended"):
            raise S3Error(400, "IllegalVersioningConfigurationException",
                          str(status))
        # bucket config is master-owned metadata: relay so the change
        # radiates to every zone instead of being reverted by the next
        # sync round's master-copy adoption
        self._forward_to_master(h, "PUT",
                                f"/{quote(bucket)}?versioning",
                                self._read_body(h))
        meta["versioning"] = status
        self._update_bucket_meta(bucket, meta)
        self._respond(h, 200)

    def _versioning_of(self, bmeta: dict) -> str:
        return bmeta.get("versioning", "")

    def _list_versions(self, h, bucket: str, q: dict) -> None:
        """GET ?versions (ref: RGWListBucketVersions)."""
        prefix = q.get("prefix", "")
        idx = self._index(bucket)
        ents = []
        for key in sorted(k for k in idx if k.startswith(prefix)
                          and not k.startswith(".upload.")):
            versions = idx[key].get("versions")
            if versions is None:
                versions = [{"vid": "null",
                             "size": idx[key]["size"],
                             "etag": idx[key]["etag"],
                             "mtime": idx[key]["mtime"], "dm": False}]
            for i, v in enumerate(versions):
                latest = str(i == 0).lower()
                if v.get("dm"):
                    ents.append(
                        f"<DeleteMarker><Key>{escape(key)}</Key>"
                        f"<VersionId>{v['vid']}</VersionId>"
                        f"<IsLatest>{latest}</IsLatest>"
                        f"<LastModified>{v['mtime']}</LastModified>"
                        "</DeleteMarker>")
                else:
                    ents.append(
                        f"<Version><Key>{escape(key)}</Key>"
                        f"<VersionId>{v['vid']}</VersionId>"
                        f"<IsLatest>{latest}</IsLatest>"
                        f"<Size>{v['size']}</Size>"
                        f"<ETag>&quot;{v['etag']}&quot;</ETag>"
                        f"<LastModified>{v['mtime']}</LastModified>"
                        "</Version>")
        self._respond(h, 200, (
            '<?xml version="1.0"?><ListVersionsResult>'
            f"<Name>{escape(bucket)}</Name>"
            f"{''.join(ents)}</ListVersionsResult>").encode())

    # -- lifecycle (ref: src/rgw/rgw_lc.cc; S3 PutBucketLifecycle) ------
    def _lifecycle_op(self, h, method: str, bucket: str) -> None:
        meta = self._require_bucket(bucket)
        if method == "GET":
            rules = meta.get("lifecycle")
            if not rules:
                raise S3Error(404, "NoSuchLifecycleConfiguration")
            ents = []
            for r in rules:
                exp = (f"<Expiration><Days>{r['days']}</Days>"
                       "</Expiration>") if r.get("days") else ""
                nce = (f"<NoncurrentVersionExpiration><NoncurrentDays>"
                       f"{r['noncurrent_days']}</NoncurrentDays>"
                       "</NoncurrentVersionExpiration>") \
                    if r.get("noncurrent_days") else ""
                ents.append(
                    f"<Rule><ID>{escape(r['id'])}</ID>"
                    f"<Prefix>{escape(r['prefix'])}</Prefix>"
                    f"<Status>{r['status']}</Status>{exp}{nce}</Rule>")
            return self._respond(h, 200, (
                '<?xml version="1.0"?><LifecycleConfiguration>'
                f"{''.join(ents)}</LifecycleConfiguration>").encode())
        if method == "DELETE":
            self._forward_to_master(h, "DELETE",
                                    f"/{quote(bucket)}?lifecycle")
            meta.pop("lifecycle", None)
            self._update_bucket_meta(bucket, meta)
            return self._respond(h, 204)
        if method != "PUT":
            raise S3Error(405, "MethodNotAllowed", method)
        try:
            root = ET.fromstring(self._read_body(h))
        except ET.ParseError:
            raise S3Error(400, "MalformedXML")
        rules = []
        for rule in root.iter():
            if not rule.tag.endswith("Rule"):
                continue
            r = {"id": "", "prefix": "", "status": "Enabled",
                 "days": 0, "noncurrent_days": 0}
            for el in rule.iter():
                tag = el.tag.rsplit("}", 1)[-1]
                if tag == "ID":
                    r["id"] = el.text or ""
                elif tag == "Prefix":
                    r["prefix"] = el.text or ""
                elif tag == "Status":
                    r["status"] = el.text or "Enabled"
                elif tag in ("Days", "NoncurrentDays"):
                    try:
                        n = int(el.text or 0)
                    except ValueError:
                        raise S3Error(400, "MalformedXML",
                                      f"bad {tag}: {el.text}")
                    r["days" if tag == "Days"
                      else "noncurrent_days"] = n
            if not r["days"] and not r["noncurrent_days"]:
                raise S3Error(400, "MalformedXML",
                              "rule needs an expiration")
            rules.append(r)
        self._forward_to_master(h, "PUT",
                                f"/{quote(bucket)}?lifecycle",
                                self._read_body(h))
        meta["lifecycle"] = rules
        self._update_bucket_meta(bucket, meta)
        self._respond(h, 200)

    # -- STS Actions (ref: rgw_rest_sts.cc RGWREST_STS dispatch) --------
    def _sts_op(self, h, method: str, q: dict) -> None:
        """Action-style STS surface on the service endpoint:
        AssumeRole mints temp credentials for the authenticated
        caller; CreateRole/DeleteRole/ListRoles administer the role
        store (ref: rgw_rest_sts.cc RGWSTSAssumeRole + the role REST
        ops in rgw_rest_role.cc)."""
        action = q.get("Action", "")
        if method != "POST" and action != "ListRoles":
            raise S3Error(405, "MethodNotAllowed", method)
        # the acting principal: SigV4-authenticated user when the
        # gateway runs a keyring, anonymous otherwise
        caller = getattr(h, "s3_user", None) or "anonymous"
        try:
            if action == "AssumeRole":
                role = q.get("RoleArn", "").rsplit("/", 1)[-1] or \
                    q.get("RoleName", "")
                dur = q.get("DurationSeconds")
                creds = self.sts.assume_role(
                    caller, role,
                    duration_s=int(dur) if dur else None)
                return self._respond(h, 200, (
                    '<?xml version="1.0"?><AssumeRoleResponse>'
                    "<AssumeRoleResult><Credentials>"
                    f"<AccessKeyId>{escape(creds['access_key_id'])}"
                    "</AccessKeyId>"
                    "<SecretAccessKey>"
                    f"{escape(creds['secret_access_key'])}"
                    "</SecretAccessKey>"
                    f"<SessionToken>{escape(creds['session_token'])}"
                    "</SessionToken>"
                    f"<Expiration>{creds['expiration']:.3f}"
                    "</Expiration></Credentials><AssumedRoleUser>"
                    "<Arn>arn:aws:sts:::assumed-role/"
                    f"{escape(creds['role'])}/{escape(caller)}</Arn>"
                    "</AssumedRoleUser></AssumeRoleResult>"
                    "</AssumeRoleResponse>").encode())
            if action == "CreateRole":
                name = q.get("RoleName", "")
                trust = [p for p in q.get("Trust", "*").split(",")
                         if p]
                kw = {}
                if q.get("MaxSessionDuration"):
                    kw["max_duration"] = int(q["MaxSessionDuration"])
                self.sts.create_role(name, trust, **kw)
                return self._respond(h, 200, (
                    '<?xml version="1.0"?><CreateRoleResponse>'
                    "<CreateRoleResult><Role><RoleName>"
                    f"{escape(name)}</RoleName>"
                    f"<Arn>arn:aws:iam:::role/{escape(name)}</Arn>"
                    "</Role></CreateRoleResult>"
                    "</CreateRoleResponse>").encode())
            if action == "DeleteRole":
                name = q.get("RoleArn", "").rsplit("/", 1)[-1] or \
                    q.get("RoleName", "")
                self.sts.delete_role(name)
                return self._respond(h, 200, b"<DeleteRoleResponse/>")
            # ListRoles
            ents = "".join(
                f"<member><RoleName>{escape(n)}</RoleName>"
                f"<Arn>arn:aws:iam:::role/{escape(n)}</Arn></member>"
                for n in sorted(self.sts.list_roles()))
            return self._respond(h, 200, (
                '<?xml version="1.0"?><ListRolesResponse>'
                f"<ListRolesResult><Roles>{ents}</Roles>"
                "</ListRolesResult></ListRolesResponse>").encode())
        except STSError as e:
            raise S3Error(e.status, e.code, e.msg)
        except ValueError as e:
            raise S3Error(400, "ValidationError", str(e))

    # -- topics + notification configs (ref: rgw_rest_pubsub.cc) --------
    def _topic_op(self, h, method: str, q: dict) -> None:
        """SNS-flavored topic admin: POST /?Action=CreateTopic&Name=x
        &push-endpoint=http://... (ref: RGWPSCreateTopicOp and
        friends — the reference exposes topics through the same
        Action-style API)."""
        action = q.get("Action", "")
        if method != "POST" and action != "ListTopics":
            # mutating Actions are POST-only (GET must stay safe)
            raise S3Error(405, "MethodNotAllowed", method)
        if action == "CreateTopic":
            name = q.get("Name", "")
            if not name:
                raise S3Error(400, "InvalidArgument", "Name")
            endpoint = q.get("push-endpoint", "")
            if endpoint and not endpoint.startswith(
                    ("http://", "https://")):
                raise S3Error(400, "InvalidArgument",
                              f"push-endpoint {endpoint}")
            self.topics.create(name, endpoint)
            return self._respond(h, 200, (
                '<?xml version="1.0"?><CreateTopicResponse>'
                f"<TopicArn>arn:aws:sns:::{escape(name)}</TopicArn>"
                "</CreateTopicResponse>").encode())
        if action == "DeleteTopic":
            self.topics.delete(q.get("TopicArn", "").rsplit(":", 1)[-1])
            return self._respond(h, 200,
                                 b"<DeleteTopicResponse/>")
        if action == "ListTopics":
            ents = "".join(
                f"<member><TopicArn>arn:aws:sns:::{escape(n)}"
                f"</TopicArn></member>"
                for n in sorted(self.topics.list()))
            return self._respond(h, 200, (
                '<?xml version="1.0"?><ListTopicsResponse>'
                f"<Topics>{ents}</Topics>"
                "</ListTopicsResponse>").encode())
        raise S3Error(400, "InvalidAction", action)

    def _notification_op(self, h, method: str, bucket: str) -> None:
        """Get/Put/DeleteBucketNotificationConfiguration."""
        meta = self._require_bucket(bucket)
        if method == "GET":
            return self._respond(h, 200, notification_xml(
                meta.get("notifications") or []))
        if method == "DELETE":
            self._forward_to_master(h, "DELETE",
                                    f"/{quote(bucket)}?notification")
            meta.pop("notifications", None)
            self._update_bucket_meta(bucket, meta)
            return self._respond(h, 204)
        if method != "PUT":
            raise S3Error(405, "MethodNotAllowed", method)
        try:
            cfgs = parse_notification_xml(self._read_body(h))
        except ValueError as e:
            raise S3Error(400, "MalformedXML", str(e))
        for cfg in cfgs:
            if self.topics.get(cfg["topic"]) is None:
                raise S3Error(400, "InvalidArgument",
                              f"no such topic {cfg['topic']}")
        self._forward_to_master(h, "PUT",
                                f"/{quote(bucket)}?notification",
                                self._read_body(h))
        meta["notifications"] = cfgs
        self._update_bucket_meta(bucket, meta)
        self._respond(h, 200)

    # -- multisite (ref: rgw_data_sync.cc; model in multisite.py) -------
    def shard_of(self, bucket: str, key: str) -> int:
        return _shard_of(key, self._nshards(bucket))

    def peer_request(self, endpoint: str, method: str, path: str,
                     body: bytes | None = None,
                     headers: dict | None = None,
                     timeout: float = 10.0):
        """HTTP to a peer zone's gateway -> (status, headers, body).
        Signed with the multisite system user's key when one is
        configured (ref: the system user's SigV4 on every sync/forward
        request) so secured peers accept it through the normal auth
        gate."""
        if self.faults is not None:
            # raises ConnectionError (an OSError — callers already
            # translate that into PeerError) when a rule severs us
            self.faults.check_http(f"rgw.{self.zone}", endpoint)
        url = endpoint.rstrip("/") + path
        hdrs = dict(headers or {})
        if self.system_key is not None:
            u = urlparse(url)
            hdrs.setdefault("host", u.netloc)
            signed_path = u.path + (f"?{u.query}" if u.query else "")
            hdrs = sign_request(method, signed_path, hdrs, body or b"",
                                *self.system_key)
        req = urllib.request.Request(url, data=body, method=method,
                                     headers=hdrs)
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()

    def _forward_to_master(self, h, method: str, path: str,
                           body: bytes = b""):
        """Metadata ops are master-owned (ref: rgw's forward_request_
        to_master): a secondary relays the op to the master zone with
        its zone in the trace — the master will not forward it back
        (trace non-empty) and will not re-fire notifications.  Returns
        the master's (status, headers, body) reply, or None when no
        forward applies (standalone gateway / already the master /
        replicated request)."""
        if self.multisite is None or self.multisite.is_master():
            return None
        if self._request_trace():
            return None         # forwarded/replicated op: terminal hop
        endpoint = self.multisite.master_endpoint()
        if not endpoint:
            return None
        try:
            return self.peer_request(
                endpoint, method, path, body or None,
                headers={ZONE_TRACE_HEADER:
                         format_zone_trace([self.zone])})
        except urllib.error.HTTPError as e:
            # the master answered and refused: relay its real verdict —
            # a 409 BucketNotEmpty on a forwarded bucket DELETE is a
            # permanent S3 error, not a retryable "master unreachable"
            code, msg = "InternalError", f"metadata master: HTTP {e.code}"
            try:
                root = ET.fromstring(e.read())
                code = root.findtext("Code") or code
                msg = root.findtext("Message") or msg
            except ET.ParseError:
                pass
            raise S3Error(e.code, code, msg)
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise S3Error(503, "ServiceUnavailable",
                          f"metadata master unreachable: {e}")

    def _admin_op(self, h, method: str, op: str, q: dict) -> None:
        """The /admin/* control surface the sync agent and the CLI
        speak: period (GET/adopt), bucket registry + index dumps,
        datalog cursor reads, sync status (ref: rgw's RESTful admin
        resources + the data-log REST ops in rgw_rest_log.cc).  On a
        secured gateway only the multisite system user may speak it —
        any tenant could otherwise forge a period adopt or dump
        another tenant's bucket index (same gate as the zone-trace
        header)."""
        if (self.keyring is not None or self.keystone is not None) \
                and (self.system_key is None or
                     getattr(h, "s3_user", None) != self.system_key[0]):
            raise S3Error(403, "AccessDenied",
                          "admin surface is system-user only")
        def respond_json(obj, status: int = 200):
            self._respond(h, status, json.dumps(obj).encode(),
                          "application/json")

        if op == "period":
            if self.multisite is None:
                raise S3Error(404, "NoSuchKey", "not a zone member")
            if method == "GET":
                return respond_json(self.multisite.admin.period_get())
            if method == "POST":
                # period push (ref: RGWPeriod push to peers /
                # `radosgw-admin period pull`): adopt if newer
                try:
                    period = json.loads(self._read_body(h))
                except ValueError:
                    raise S3Error(400, "InvalidArgument", "bad JSON")
                adopted = self.multisite.admin.period_adopt(period)
                if adopted:
                    self.multisite.refresh(force=True)
                return respond_json(
                    {"adopted": adopted,
                     "epoch": self.multisite.epoch})
            raise S3Error(405, "MethodNotAllowed", method)
        if op == "buckets" and method == "GET":
            # raw: peers need the deletion tombstones too
            return respond_json(self._buckets_raw())
        if op == "bucket" and method == "GET":
            name = q.get("name", "")
            if name not in self._buckets():
                raise S3Error(404, "NoSuchBucket", name)
            # in-flight multipart bookkeeping (.upload.*) shares the
            # index omap but is not object state — a peer's full sync
            # must see objects only.  The dump marks a full-sync
            # floor: the puller's incremental cursors start at the
            # CURRENT datalog heads, so age-trim must spare newer
            # records until the grace passes.
            self.note_fullsync_dump(name)
            return respond_json(
                {k: v for k, v in self._index(name).items()
                 if not k.startswith(".upload.")})
        if op == "log" and method == "POST":
            try:
                d = json.loads(self._read_body(h))
                markers = {int(s): int(m)
                           for s, m in d.get("markers", {}).items()}
            except (ValueError, AttributeError):
                raise S3Error(400, "InvalidArgument", "bad JSON")
            bucket = d.get("bucket", "")
            if bucket not in self._buckets():
                raise S3Error(404, "NoSuchBucket", bucket)
            batch = int(d.get("max", 64))
            shards = {}
            for s, marker in markers.items():
                entries, head = self.datalog.list(bucket, s, marker,
                                                  batch)
                shards[str(s)] = {"entries": entries, "head": head}
            return respond_json({"shards": shards})
        if op == "sync-status" and method == "GET":
            if self.sync is None:
                raise S3Error(404, "NoSuchKey", "not a zone member")
            return respond_json(self.sync.status())
        if op == "sync-markers" and method == "GET":
            # a source zone asks: how far have YOU durably applied my
            # datalog?  Feeds the source's auto-trim (datalog records
            # behind every registered peer's durable cursor may go)
            if self.sync is None:
                raise S3Error(404, "NoSuchKey", "not a zone member")
            return respond_json(
                self.sync.markers_for(q.get("source", "")))
        raise S3Error(404, "NoSuchKey", f"admin/{op}")

    def sync_ensure_bucket(self, bucket: str, meta: dict,
                           from_master: bool = False,
                           registry: dict | None = None) -> None:
        """Make the peer's bucket exist here with the peer's shard
        layout; config fields (versioning/lifecycle/notifications)
        follow the metadata master's copy — metadata ops are
        master-owned, so only the master's view overwrites ours.
        `registry` is the caller's one-read-per-round snapshot of
        _buckets_raw() (the sync agent calls this for every peer
        bucket every tick — N fresh registry fetches per round
        otherwise)."""
        cur = (registry if registry is not None
               else self._buckets_raw()).get(bucket)
        if cur is not None and "deleted" in cur:
            if meta.get("created", "") > cur["deleted"]:
                cur = None      # recreated since our tombstone
            else:
                return          # we know it was deleted; the peer's
                # live copy is the stale side
        if cur is None:
            rec = {"created": meta.get("created", self._now_str()),
                   "shards": int(meta.get("shards",
                                          self.index_shards))}
            for fld in ("versioning", "lifecycle", "notifications"):
                if fld in meta:
                    rec[fld] = meta[fld]
            self._update_bucket_meta(bucket, rec)
            for shard in range(rec["shards"]):
                try:
                    self.io.create(_index_obj(bucket, shard))
                except RadosError:
                    pass
            return
        if not from_master:
            return
        changed = False
        for fld in ("versioning", "lifecycle", "notifications"):
            if meta.get(fld) != cur.get(fld):
                if fld in meta:
                    cur[fld] = meta[fld]
                else:
                    cur.pop(fld, None)
                changed = True
        if changed:
            self._update_bucket_meta(bucket, cur)

    def sync_drop_bucket(self, bucket: str, meta: dict,
                         registry: dict | None = None) -> bool:
        """Apply a peer's bucket-deletion tombstone: drop the local
        bucket — including any objects our copy still holds.  The
        origin could only delete an EMPTY bucket, and deleting it
        destroyed its index shards and their datalogs, so the final
        object deletes can never replicate: waiting for them would
        wedge a lagging replica forever while reporting caught up.
        The converged state IS empty — discard and gc.  Returns True
        when the local registry reflects the deletion."""
        cur = (registry if registry is not None
               else self._buckets_raw()).get(bucket)
        if cur is None or "deleted" in cur:
            return True
        if cur.get("created", "") > meta.get("deleted", ""):
            return False        # recreated since: the tombstone is
            # the stale side
        objs = self._bucket_data_objs(bucket)
        self._delete_bucket(bucket, deleted_at=meta.get("deleted"))
        if objs:
            self._remove_objs(objs, defer=True)
        return True

    def _bucket_data_objs(self, bucket: str) -> list[str]:
        """Every live data object the bucket's index references — the
        gc list when a whole local copy is discarded (tombstone drop,
        incarnation reset)."""
        objs = []
        for ent in self._index(bucket).values():
            if ent.get("versions") is not None:
                objs += [v["obj"] for v in ent["versions"]
                         if v.get("obj") and not v.get("dm")]
            elif ent.get("obj"):
                objs.append(ent["obj"])
        return objs

    def sync_reset_bucket(self, bucket: str, meta: dict,
                          registry: dict | None = None) -> None:
        """The peer's bucket is a NEW incarnation (its created stamp
        changed while we held the old copy: a delete + recreate we
        slept through).  The old incarnation's datalog died with its
        bucket, so its object deletes can never replicate — our stale
        objects would be served and listed here forever while deleted
        cluster-wide.  Same resolution as sync_drop_bucket: discard
        the old copy, the caller's full sync rebuilds from the new
        incarnation's listing.  No-op when our copy already IS the
        new incarnation (its creation propagated here normally)."""
        reg = registry if registry is not None else self._buckets_raw()
        cur = reg.get(bucket)
        if cur is None or "deleted" in cur or \
                cur.get("created", "") == meta.get("created", ""):
            return
        objs = self._bucket_data_objs(bucket)
        self._delete_bucket(bucket, tombstone=False)
        reg.pop(bucket, None)
        if objs:
            self._remove_objs(objs, defer=True)

    def sync_apply(self, bucket: str, ent: dict, data: bytes | None,
                   src: str, nshards: int | None = None) -> bool:
        """Apply one replicated datalog entry: stage the bytes (puts),
        then run the idempotent obj_sync_apply index transaction with
        the trace extended by the source + this zone — the re-logged
        entry lets further zones pull the change without looping.
        Returns whether local state changed.  `nshards` (the local
        layout) saves a per-entry registry fetch on catch-up."""
        key = ent["key"]
        trace = list(ent.get("trace") or ())
        for z in (src, self.zone):
            if z and z not in trace:
                trace.append(z)
        mode = ent.get("mode", "plain")
        obj = None
        obj_unique = False
        if ent["op"] == "put":
            # same staging discipline as _store_object: fresh object,
            # linked (or dropped) by the index transaction's verdict
            gen = uuid.uuid4().hex
            if not ent.get("vid"):
                obj, obj_unique = f"{bucket}/{key}#{gen}", True
            elif ent["vid"] == "null":
                obj, obj_unique = f"{bucket}/{key}@null.{gen}", True
            else:
                # deterministic name: a replay restages the SAME
                # object an earlier apply linked — never gc it on skip
                obj = f"{bucket}/{key}@{ent['vid']}"
            self.io.write_full(obj, data or b"")
        out = self._index_exec(bucket, key, "obj_sync_apply", {
            "op": ent["op"], "vid": ent.get("vid"),
            "size": ent.get("size", 0), "etag": ent.get("etag", ""),
            "mtime": ent.get("mtime", ""), "mode": mode, "obj": obj,
            "log": {"trace": trace}}, nshards=nshards)
        if not out.get("applied") and obj_unique:
            # never linked, no reader can hold it: collect now
            self._remove_objs([obj], defer=False)
        return bool(out.get("applied"))

    @staticmethod
    def _parse_mtime(s: str) -> float:
        # one parser for writer and OSD-side trimmer (cls/rgw.py)
        return parse_mtime(s)

    def lc_tick(self, now: float | None = None) -> int:
        """One lifecycle pass (ref: RGWLC::process — the reference
        runs it from a worker; here the gateway's maintenance tick or
        the caller drives it).  Returns expirations performed.
        Expiring the CURRENT version of a versioned object inserts a
        delete marker (S3 semantics); noncurrent expiration removes
        old versions outright."""
        now = time.time() if now is None else now
        acted = 0
        for bucket, meta in self._buckets().items():
            rules = [r for r in meta.get("lifecycle", [])
                     if r.get("status") == "Enabled"]
            if not rules:
                continue
            versioned = bool(self._versioning_of(meta))
            idx = self._index(bucket)
            for key, ent in idx.items():
                if key.startswith(".upload."):
                    continue
                acted_on_key = False
                for r in rules:
                    if acted_on_key:
                        # one action per key per tick: a second
                        # matching rule would act on a stale
                        # snapshot (stacked delete markers)
                        break
                    if not key.startswith(r["prefix"]):
                        continue
                    if r.get("days"):
                        age = now - self._parse_mtime(
                            ent.get("mtime", ""))
                        latest_dm = bool((ent.get("versions") or
                                          [{}])[0].get("dm"))
                        if age > r["days"] * 86400 and not latest_dm:
                            # expiry decided on this tick's snapshot;
                            # the cls guard cancels it if the head
                            # moved meanwhile (fresh PUT wins)
                            try:
                                if versioned or ent.get("versions"):
                                    hv = (ent.get("versions")
                                          or [{"vid": "null"}])[0]
                                    self._insert_delete_marker(
                                        bucket, key,
                                        guard={"if_head_vid":
                                               hv["vid"],
                                               "if_mtime":
                                               hv.get("mtime",
                                                      ent.get(
                                                          "mtime",
                                                          ""))})
                                else:
                                    self._index_exec(
                                        bucket, key,
                                        "obj_delete_plain",
                                        {"plain_obj":
                                         _data_obj(bucket, key),
                                         "if_mtime":
                                         ent.get("mtime", "")})
                                acted += 1
                                self._notify_event(
                                    bucket, key,
                                    "s3:LifecycleExpiration:"
                                    "DeleteMarkerCreated"
                                    if versioned or
                                    ent.get("versions") else
                                    "s3:LifecycleExpiration:Delete",
                                    0, "", bmeta=meta)
                            except RadosError as e:
                                if e.errno_name != "ECANCELED":
                                    raise
                            acted_on_key = True
                            continue
                    if r.get("noncurrent_days") and \
                            ent.get("versions"):
                        out = self._index_exec(
                            bucket, key, "obj_trim_noncurrent",
                            {"now": now,
                             "max_age_s":
                             r["noncurrent_days"] * 86400})
                        if out.get("dropped"):
                            acted += out["dropped"]
                            acted_on_key = True
        return acted

    def _list_objects(self, h, bucket: str, q: dict) -> None:
        """ListObjectsV2 (ref: RGWListBucket)."""
        prefix = q.get("prefix", "")
        max_keys = int(q.get("max-keys", 1000))
        token = q.get("continuation-token", "")
        idx = self._index(bucket)
        keys = sorted(k for k in idx
                      if k.startswith(prefix) and k > token
                      and not k.startswith(".upload.")
                      and not idx[k].get("dm"))   # delete markers hide
        page, truncated = keys[:max_keys], len(keys) > max_keys
        ents = "".join(
            f"<Contents><Key>{escape(k)}</Key>"
            f"<Size>{idx[k]['size']}</Size>"
            f"<ETag>&quot;{idx[k]['etag']}&quot;</ETag>"
            f"<LastModified>{idx[k]['mtime']}</LastModified>"
            "</Contents>" for k in page)
        nxt = (f"<NextContinuationToken>{escape(page[-1])}"
               "</NextContinuationToken>") if truncated else ""
        self._respond(h, 200, (
            '<?xml version="1.0"?><ListBucketResult>'
            f"<Name>{escape(bucket)}</Name>"
            f"<Prefix>{escape(prefix)}</Prefix>"
            f"<KeyCount>{len(page)}</KeyCount>"
            f"<IsTruncated>{str(truncated).lower()}</IsTruncated>"
            f"{nxt}{ents}</ListBucketResult>").encode())

    #: index-omap namespaces the gateway owns — a client object with
    #: one of these names would be parsed as bookkeeping (a PUT named
    #: `.dlmeta` wedges the shard's datalog head)
    RESERVED_KEY_PREFIXES = (DL_PREFIX, DL_META, ".upload.", ".part.")

    # -- object level ----------------------------------------------------
    def _object_op(self, h, method: str, bucket: str, key: str,
                   q: dict) -> None:
        if key.startswith(self.RESERVED_KEY_PREFIXES):
            if method in ("PUT", "POST", "DELETE"):
                raise S3Error(400, "InvalidArgument",
                              f"reserved key namespace: {key}")
            # reads: the bookkeeping record is not an object — serving
            # it would crash on the missing etag/size fields (500/
            # connection reset instead of a clean miss)
            raise S3Error(404, "NoSuchKey", key)
        bmeta = self._require_bucket(bucket)
        nshards = int(bmeta.get("shards", 1))
        if method == "POST" and "uploads" in q:
            return self._initiate_multipart(h, bucket, key)
        if method == "POST" and "uploadId" in q:
            return self._complete_multipart(h, bucket, key,
                                            q["uploadId"], bmeta)
        if method == "PUT" and "uploadId" in q:
            return self._upload_part(h, bucket, key, q)
        if method == "DELETE" and "uploadId" in q:
            return self._abort_multipart(h, bucket, key, q["uploadId"])
        if method == "PUT" and "x-amz-copy-source" in h.headers:
            return self._copy_object(h, bucket, key, bmeta)
        if method == "PUT":
            return self._put_object(h, bucket, key, bmeta)
        meta = self._index_entry(bucket, key, nshards)
        if meta is None:
            raise S3Error(404, "NoSuchKey", key)
        want_vid = q.get("versionId", "")
        if method in ("HEAD", "GET"):
            if method == "HEAD":
                v = self._select_version(meta, want_vid, key)
                return self._respond(
                    h, 200, b"", "application/octet-stream",
                    {"ETag": f'"{v["etag"]}"',
                     "Content-Length": str(v["size"]),
                     "x-amz-version-id": v.get("vid", "null")})
            v, data = self._read_version_data(bucket, key, meta,
                                              want_vid)
            return self._respond(h, 200, data,
                                 "application/octet-stream",
                                 {"ETag": f'"{v["etag"]}"',
                                  "x-amz-version-id":
                                      v.get("vid", "null")})
        if method == "DELETE":
            return self._delete_object(h, bucket, key, bmeta, meta,
                                       want_vid)
        raise S3Error(405, "MethodNotAllowed", method)

    def _read_version_data(self, bucket: str, key: str, meta: dict,
                           want_vid: str) -> tuple[dict, bytes]:
        """Resolve + read the served version's bytes.  If a racing
        overwrite garbage-collected our object between the index read
        and the data read (the GC grace window lost the race), the
        index is re-resolved ONCE — the fresh entry names the new
        object, so the reader gets consistent (headers, bytes) instead
        of a 500."""
        v = self._select_version(meta, want_vid, key)
        try:
            return v, self.io.read(v.get("obj")
                                   or _data_obj(bucket, key))
        except RadosError as e:
            if e.errno_name != "ENOENT":
                raise
            meta2 = self._index_entry(bucket, key)
            if meta2 is None:
                raise S3Error(404, "NoSuchKey", key)
            v2 = self._select_version(meta2, want_vid, key)
            return v2, self.io.read(v2.get("obj")
                                    or _data_obj(bucket, key))

    def _select_version(self, meta: dict, vid: str, key: str) -> dict:
        """The version a read serves: the newest live one, or the
        explicitly requested versionId (ref: rgw versioned read
        resolution)."""
        versions = meta.get("versions")
        if versions is None:
            if vid and vid != "null":
                raise S3Error(404, "NoSuchVersion", vid)
            return meta
        if vid:
            for v in versions:
                if v["vid"] == vid:
                    if v.get("dm"):
                        raise S3Error(405, "MethodNotAllowed",
                                      "delete marker")
                    return v
            raise S3Error(404, "NoSuchVersion", vid)
        if versions[0].get("dm"):
            raise S3Error(404, "NoSuchKey", key)
        return versions[0]

    def _now_str(self) -> str:
        return now_str()

    def _store_versions(self, bucket: str, key: str,
                        versions: list) -> None:
        """Administrative stack rewrite (tests back-dating mtimes,
        offline surgery).  NOT the client data path — that runs
        through the cls_rgw index transactions above."""
        shard = _shard_of(key, self._nshards(bucket))
        if not versions:
            self.io.remove_omap_keys(_index_obj(bucket, shard), [key])
            return
        head = versions[0]
        meta = {"versions": versions, "size": head.get("size", 0),
                "etag": head.get("etag", ""), "mtime": head["mtime"],
                "dm": bool(head.get("dm"))}
        self.io.set_omap(_index_obj(bucket, shard),
                         {key: json.dumps(meta).encode()})

    def _insert_delete_marker(self, bucket: str, key: str,
                              vid: str | None = None,
                              replace_null: bool = False,
                              guard: dict | None = None) -> str:
        out = self._index_exec(bucket, key, "obj_delete_marker", dict(
            guard or {}, vid=vid or uuid.uuid4().hex,
            mtime=self._now_str(), replace_null=replace_null,
            plain_obj=_data_obj(bucket, key)))
        return out["vid"]

    def _delete_object(self, h, bucket: str, key: str, bmeta: dict,
                       meta: dict, want_vid: str) -> None:
        """Versioned deletes (ref: rgw delete marker flow): no
        versionId = insert a delete marker (Enabled) or replace the
        null version with one (Suspended); an explicit versionId
        removes that version outright.  Every index RMW runs on the
        OSD (cls/rgw.py) — concurrent gateways stay consistent."""
        versioning = self._versioning_of(bmeta)
        plain_obj = _data_obj(bucket, key)
        if want_vid:
            try:
                self._index_exec(bucket, key, "obj_delete_version",
                                 {"vid": want_vid,
                                  "plain_obj": plain_obj})
            except RadosError as e:
                if e.errno_name == "ENOENT":
                    raise S3Error(404, "NoSuchVersion", want_vid)
                raise
            self._notify_event(bucket, key, "s3:ObjectRemoved:Delete",
                               0, "", want_vid, bmeta)
            return self._respond(h, 204, headers={
                "x-amz-version-id": want_vid})
        if not versioning and meta.get("versions") is None:
            try:
                self._index_exec(bucket, key, "obj_delete_plain",
                                 {"plain_obj": plain_obj})
                self._notify_event(bucket, key,
                                   "s3:ObjectRemoved:Delete", 0, "",
                                   bmeta=bmeta)
                return self._respond(h, 204)
            except RadosError as e:
                if e.errno_name != "ECANCELED":
                    raise
                # a concurrent versioned PUT grew a stack under us:
                # fall through to the delete-marker path
        vid = self._insert_delete_marker(
            bucket, key, vid="null" if versioning == "Suspended"
            else None, replace_null=versioning == "Suspended")
        self._notify_event(bucket, key,
                           "s3:ObjectRemoved:DeleteMarkerCreated",
                           0, "", vid, bmeta)
        self._respond(h, 204, headers={"x-amz-delete-marker": "true",
                                       "x-amz-version-id": vid})

    def _index_exec(self, bucket: str, key: str, method: str,
                    indata: dict, nshards: int | None = None) -> dict:
        """Run a cls_rgw index transaction on the key's index shard.
        The RMW executes inside the OSD (cls/rgw.py) so concurrent
        gateways serialize on the PG — the reference's cls_rgw
        contract (ref: src/cls/rgw/cls_rgw.cc), replacing the old
        gateway-local _vlock which could not protect two processes."""
        if nshards is None:
            nshards = self._nshards(bucket)
        if self.zone is not None and "log" not in indata:
            # zone member: every index mutation also appends its
            # datalog record — in the SAME cls transaction.  The trace
            # is the request's (forwarded/replicated writes carry one)
            # extended with this zone.
            indata = dict(indata, log={
                "trace": self._request_trace() + [self.zone]})
        iobj = _index_obj(bucket, _shard_of(key, nshards))
        out = self.io.exec(iobj, "rgw", method,
                           dict(indata, key=key)) or {}
        self._remove_objs(out.get("removed", ()))
        return out

    def _remove_objs(self, objs, defer: bool = True) -> None:
        """Delete data objects AFTER their index commit orphaned them
        (index-first ordering: a crash leaves garbage, never a
        dangling index entry — the reference's gc does the same).
        Deletion is deferred GC_GRACE_S so a reader holding the old
        index entry can still finish; defer=False is for objects no
        reader can have seen (never-linked staging writes)."""
        if defer:
            expire = time.time() + self.GC_GRACE_S
            with self._gc_lock:
                self._gc_queue.extend((expire, o) for o in objs)
            return
        for obj in objs:
            try:
                self.io.remove(obj)
            except RadosError:
                pass

    def _store_object(self, bucket: str, key: str, data: bytes,
                      etag: str, bmeta: dict | None = None) -> str | None:
        """Write object data, then commit the index transaction on the
        OSD; returns the new version id (None = unversioned bucket)."""
        bmeta = bmeta if bmeta is not None \
            else self._require_bucket(bucket)
        versioning = self._versioning_of(bmeta)
        nshards = int(bmeta.get("shards", 1))
        # every write lands in a FRESH object; the index transaction
        # links it and reports what it orphaned (the reference's
        # instance-object model) — an overwrite never clobbers bytes
        # a concurrent reader or a surprise version stack still needs
        gen = uuid.uuid4().hex
        if versioning == "Enabled":
            vid, mode = gen, "enabled"
            obj = f"{bucket}/{key}@{vid}"
        elif versioning == "Suspended":
            vid, mode = "null", "suspended"
            obj = f"{bucket}/{key}@null.{gen}"
        else:
            vid, mode = "", "plain"
            obj = f"{bucket}/{key}#{gen}"
        self.io.write_full(obj, data)
        try:
            out = self._index_exec(bucket, key, "obj_store", {
                "mode": mode, "vid": vid, "size": len(data),
                "etag": etag, "mtime": self._now_str(), "obj": obj,
                "plain_obj": _data_obj(bucket, key)}, nshards)
        except RadosError as e:
            if e.errno_name != "ECANCELED" or mode != "plain":
                raise
            # the entry grew a version stack under us (versioning
            # enabled concurrently): drop the unlinked staging object
            # and retry with fresh bucket meta
            self._remove_objs([obj], defer=False)
            return self._store_object(bucket, key, data, etag)
        return out.get("vid")

    def _put_object(self, h, bucket: str, key: str,
                    bmeta: dict | None = None) -> None:
        data = self._read_body(h)
        etag = hashlib.md5(data).hexdigest()
        vid = self._store_object(bucket, key, data, etag, bmeta)
        self._notify_event(bucket, key, "s3:ObjectCreated:Put",
                           len(data), etag, vid, bmeta)
        hdrs = {"ETag": f'"{etag}"'}
        if vid is not None:
            hdrs["x-amz-version-id"] = vid
        self._respond(h, 200, headers=hdrs)

    def _copy_object(self, h, bucket: str, key: str,
                     bmeta: dict | None = None) -> None:
        """Server-side copy (ref: RGWCopyObj; x-amz-copy-source)."""
        src = unquote(h.headers["x-amz-copy-source"]).lstrip("/")
        if "/" not in src:
            raise S3Error(400, "InvalidArgument", src)
        s_bucket, s_key = src.split("/", 1)
        if s_key.startswith(self.RESERVED_KEY_PREFIXES):
            # bookkeeping records are not copyable objects (serving
            # one would crash on its missing etag/size fields)
            raise S3Error(404, "NoSuchKey", s_key)
        self._require_bucket(s_bucket)
        s_meta = self._index_entry(s_bucket, s_key)
        if s_meta is None:
            raise S3Error(404, "NoSuchKey", s_key)
        _, data = self._read_version_data(s_bucket, s_key, s_meta, "")
        etag = hashlib.md5(data).hexdigest()
        vid = self._store_object(bucket, key, data, etag, bmeta)
        self._notify_event(bucket, key, "s3:ObjectCreated:Copy",
                           len(data), etag, vid, bmeta)
        self._respond(h, 200, (
            '<?xml version="1.0"?><CopyObjectResult>'
            f"<ETag>&quot;{etag}&quot;</ETag>"
            f"<LastModified>{s_meta['mtime']}</LastModified>"
            "</CopyObjectResult>").encode())

    # -- multipart (ref: rgw RGWInitMultipart/CompleteMultipart) ---------
    def _initiate_multipart(self, h, bucket: str, key: str) -> None:
        upload_id = uuid.uuid4().hex
        self.io.set_omap(self._upload_shard(bucket, upload_id), {
            f".upload.{upload_id}": json.dumps(
                {"key": key, "parts": {}}).encode()})
        self._respond(h, 200, (
            '<?xml version="1.0"?><InitiateMultipartUploadResult>'
            f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
            f"<UploadId>{upload_id}</UploadId>"
            "</InitiateMultipartUploadResult>").encode())

    def _upload_shard(self, bucket: str, upload_id: str) -> str:
        return _index_obj(bucket, _shard_of(f".upload.{upload_id}",
                                            self._nshards(bucket)))

    def _upload_meta(self, bucket: str, upload_id: str) -> dict:
        vals = self.io.get_omap_vals_by_keys(
            self._upload_shard(bucket, upload_id),
            [f".upload.{upload_id}"])
        if not vals:
            raise S3Error(404, "NoSuchUpload", upload_id)
        return json.loads(vals[f".upload.{upload_id}"])

    def _upload_part(self, h, bucket: str, key: str, q: dict) -> None:
        upload_id = q["uploadId"]
        n = int(q.get("partNumber", 1))
        meta = self._upload_meta(bucket, upload_id)
        data = self._read_body(h)
        etag = hashlib.md5(data).hexdigest()
        part_obj = f".part.{upload_id}.{n}"
        self.io.write_full(part_obj, data)
        meta["parts"][str(n)] = {"size": len(data), "etag": etag}
        self.io.set_omap(self._upload_shard(bucket, upload_id), {
            f".upload.{upload_id}": json.dumps(meta).encode()})
        self._respond(h, 200, headers={"ETag": f'"{etag}"'})

    def _complete_multipart(self, h, bucket: str, key: str,
                            upload_id: str,
                            bmeta: dict | None = None) -> None:
        meta = self._upload_meta(bucket, upload_id)
        body = self._read_body(h)
        wanted = []
        if body:
            root = ET.fromstring(body)
            for p in root.iter():
                if p.tag.endswith("PartNumber"):
                    wanted.append(int(p.text))
        if not wanted:
            wanted = sorted(int(n) for n in meta["parts"])
        blob = bytearray()
        etags = []
        for n in wanted:
            if str(n) not in meta["parts"]:
                raise S3Error(400, "InvalidPart", str(n))
            blob += self.io.read(f".part.{upload_id}.{n}")
            etags.append(meta["parts"][str(n)]["etag"])
        etag = hashlib.md5(
            b"".join(bytes.fromhex(e) for e in etags)).hexdigest() \
            + f"-{len(wanted)}"
        vid = self._store_object(bucket, key, bytes(blob), etag,
                                 bmeta)
        self._notify_event(bucket, key,
                           "s3:ObjectCreated:CompleteMultipartUpload",
                           len(blob), etag, vid, bmeta)
        self._cleanup_upload(bucket, upload_id, meta)
        self._respond(h, 200, (
            '<?xml version="1.0"?><CompleteMultipartUploadResult>'
            f"<Key>{escape(key)}</Key><ETag>&quot;{etag}&quot;</ETag>"
            "</CompleteMultipartUploadResult>").encode())

    def _abort_multipart(self, h, bucket: str, key: str,
                         upload_id: str) -> None:
        meta = self._upload_meta(bucket, upload_id)
        self._cleanup_upload(bucket, upload_id, meta)
        self._respond(h, 204)

    def _cleanup_upload(self, bucket: str, upload_id: str,
                        meta: dict) -> None:
        for n in meta["parts"]:
            try:
                self.io.remove(f".part.{upload_id}.{n}")
            except RadosError:
                pass
        self.io.remove_omap_keys(self._upload_shard(bucket, upload_id),
                                 [f".upload.{upload_id}"])


def main(argv=None) -> int:
    """radosgw entrypoint: serve S3 over a TCP cluster."""
    import argparse
    ap = argparse.ArgumentParser(prog="ceph-tpu-rgw")
    ap.add_argument("--monmap", required=True)
    ap.add_argument("--keyring", default="",
                    help="keyring JSON (secure clusters / SigV4 auth)")
    ap.add_argument("--port", type=int, default=7480)
    ap.add_argument("--pool", default="rgw")
    a = ap.parse_args(argv)
    import os
    from ..client import Rados
    from ..tools.rados_cli import _net_from_monmap
    net = _net_from_monmap(a.monmap, getattr(a, "keyring", ""))
    r = Rados(net,
              name=f"client.rgw{os.getpid() % 10000}").connect()
    gw = RGWGateway(r, pool=a.pool, port=a.port)
    gw.start()
    print(f"rgw: serving S3 on :{gw.port} pool={a.pool}", flush=True)
    import signal
    ev = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: ev.set())
    try:
        ev.wait()
    except KeyboardInterrupt:
        pass
    gw.shutdown()
    r.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
