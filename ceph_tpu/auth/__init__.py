"""cephx-lite: shared-secret authentication with session tickets.

The reference's cephx (ref: src/auth/cephx/CephxProtocol.{h,cc}) in
reduced form, keeping the protocol shape:

* a **KeyRing** holds per-entity secrets; the mon holds everyone's
  (ref: src/auth/KeyRing.cc, mon AuthMonitor's key server);
* a client proves identity with an HMAC over a fresh nonce + server
  challenge (ref: CephxAuthorizer's challenge round-trip), and both
  sides DERIVE the session key from (entity secret, nonce, challenge)
  — it never crosses the wire, mirroring how cephx wraps the session
  key under the entity secret;
* the mon answers with a **ticket**: the session key + entity +
  expiry, sealed under the *service secret* every daemon shares
  (ref: service ticket encrypted with the service's rotating key) —
  daemons can open it; clients cannot forge it;
* afterwards every message carries `auth = (ticket, sig)` where sig
  is an HMAC under the session key over the message header AND
  payload fields, the msgr-v2 message-signing analogue
  (ref: CEPHX_REQUIRE_SIGNATURES / ProtocolV2 auth signatures): a
  captured ticket cannot be replayed onto a forged op.

Sealing is authenticate-only (HMAC tag, no confidentiality): the
threat model this layer exists to test is impersonation and
unauthorized cluster access, not wire snooping; swap `_seal/_open`
for AES-GCM to get the rest.

Modes (ref: auth_cluster_required option): "none" (default) or
"cephx".
"""
from __future__ import annotations

import hashlib
import hmac as _hmac
import json
import os
import time

from ..common.log import dout
from ..msg.messages import MAuthReply, MAuthRequest

SERVICE_ENTITY = "service"           # the shared service-secret slot


def generate_key() -> str:
    return os.urandom(16).hex()


def _mac(secret: str, blob: bytes) -> str:
    return _hmac.new(secret.encode(), blob,
                     hashlib.sha256).hexdigest()


class KeyRing:
    """entity -> secret (ref: src/auth/KeyRing.h).  JSON file format:
    {"osd.0": "<hex>", ...}."""

    def __init__(self, keys: dict[str, str] | None = None):
        self.keys: dict[str, str] = dict(keys or {})

    @classmethod
    def generate(cls, entities) -> "KeyRing":
        kr = cls({SERVICE_ENTITY: generate_key()})
        for e in entities:
            kr.keys[e] = generate_key()
        return kr

    @classmethod
    def load(cls, path: str) -> "KeyRing":
        with open(path) as f:
            return cls(json.load(f))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.keys, f, indent=1)

    def get(self, entity: str) -> str | None:
        return self.keys.get(entity)

    def subset(self, *entities: str) -> "KeyRing":
        """A daemon's keyring: its own key + the service secret."""
        return KeyRing({e: self.keys[e] for e in
                        (*entities, SERVICE_ENTITY) if e in self.keys})


def _derive_session_key(secret: str, nonce: str, challenge: str) -> str:
    return _mac(secret, f"session|{nonce}|{challenge}".encode())


def _seal(secret: str, payload: dict) -> dict:
    blob = json.dumps(payload, sort_keys=True)
    return {"blob": blob, "tag": _mac(secret, blob.encode())}


def _open(secret: str, sealed: dict) -> dict | None:
    if not isinstance(sealed, dict) or "blob" not in sealed:
        return None
    if not _hmac.compare_digest(
            _mac(secret, sealed["blob"].encode()),
            sealed.get("tag", "")):
        return None
    return json.loads(sealed["blob"])


def _canon(msg) -> bytes:
    """Byte-stable digest input covering header AND payload: a
    captured ticket must not be reattachable to a forged op (the TCP
    transport is reachable by unauthenticated processes).  Pickle of
    the field tuple is deterministic for our message payloads
    (primitives/dicts/dataclasses; dict insertion order survives the
    unpickle, so receiver-side re-canonicalization matches)."""
    import dataclasses
    import pickle
    fields = tuple((f.name, getattr(msg, f.name))
                   for f in dataclasses.fields(msg)
                   if f.name != "auth")
    return pickle.dumps((msg.type_name, fields), protocol=4)


class CephxServer:
    """Mon-side authenticator (ref: CephxServiceHandler +
    AuthMonitor's key server)."""

    def __init__(self, keyring: KeyRing,
                 ticket_ttl: float = 3600.0):
        self.keyring = keyring
        self.ttl = ticket_ttl

    def handle_request(self, msg: MAuthRequest) -> MAuthReply:
        secret = self.keyring.get(msg.entity)
        challenge = os.urandom(8).hex()
        if secret is None:
            return MAuthReply(result=-1, errstr="unknown entity")
        want = _mac(secret, f"auth|{msg.entity}|{msg.nonce}".encode())
        if not _hmac.compare_digest(want, msg.sig):
            dout("auth", 1).write("cephx: bad signature from %s",
                                  msg.entity)
            return MAuthReply(result=-13, errstr="bad signature")
        # fresh challenge binds the session key to this exchange
        session_key = _derive_session_key(secret, msg.nonce, challenge)
        ticket = _seal(self.keyring.get(SERVICE_ENTITY), {
            "entity": msg.entity, "session_key": session_key,
            "expires": time.time() + self.ttl})
        return MAuthReply(result=0, challenge=challenge,
                          ticket=ticket)


class CephxClient:
    """Per-daemon/client signer (ref: CephxClientHandler)."""

    def __init__(self, entity: str, secret: str):
        self.entity = entity
        self.secret = secret
        self.nonce = os.urandom(8).hex()
        self.session_key: str | None = None
        self.ticket: dict | None = None

    def build_request(self) -> MAuthRequest:
        self.nonce = os.urandom(8).hex()
        return MAuthRequest(
            entity=self.entity, nonce=self.nonce,
            sig=_mac(self.secret,
                     f"auth|{self.entity}|{self.nonce}".encode()))

    def ingest_reply(self, msg: MAuthReply) -> bool:
        if msg.result != 0:
            return False
        self.session_key = _derive_session_key(
            self.secret, self.nonce, msg.challenge)
        self.ticket = msg.ticket
        return True

    @property
    def authenticated(self) -> bool:
        return self.session_key is not None

    @classmethod
    def self_mint(cls, entity: str,
                  service_secret: str,
                  ttl: float = 365 * 86400.0) -> "CephxClient":
        """Daemon-side shortcut: an entity that HOLDS the service
        secret (mon/osd/mds — the reference distributes rotating
        service keys to daemons) mints its own ticket locally instead
        of doing the wire handshake."""
        c = cls(entity, service_secret)
        c.session_key = generate_key()
        c.ticket = _seal(service_secret, {
            "entity": entity, "session_key": c.session_key,
            "expires": time.time() + ttl})
        return c

    def sign(self, msg):
        """Attach (ticket, sig) to an outgoing message copy."""
        if self.session_key is None:
            return msg
        msg.auth = {"ticket": self.ticket,
                    "sig": _mac(self.session_key, _canon(msg))}
        return msg


class CephxVerifier:
    """Service-side message gate (ref: the require-signatures check in
    Protocol/ms_verify_authorizer)."""

    #: always-allowed types: the auth handshake itself, plus replies
    #: going TO clients (verified by them only if they hold keys)
    EXEMPT = {"MAuthRequest", "MAuthReply"}

    def __init__(self, service_secret: str):
        self.service_secret = service_secret

    def verify(self, msg) -> bool:
        if msg.type_name in self.EXEMPT:
            return True
        auth = getattr(msg, "auth", None)
        if not auth:
            return False
        ticket = _open(self.service_secret, auth.get("ticket"))
        if ticket is None or ticket["expires"] < time.time():
            return False
        want = _mac(ticket["session_key"], _canon(msg))
        return _hmac.compare_digest(want, auth.get("sig", ""))
