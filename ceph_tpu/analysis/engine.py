"""cephck rule engine: file walking, suppression baseline, reporting.

Rules are small classes (see rules.py) with an ``id``, a ``doc``
explaining how to read a finding, and ``check(ctx)`` yielding
Findings over one parsed file.  The engine owns everything around
them: collecting files, parsing once, matching findings against the
suppression baseline and inline ``# cephck: ignore[rule]`` markers,
and turning the result into an exit code the ship gate can trust.

v2 runs in two phases: every file is parsed first and folded into a
ProjectContext (symbol table + call graph, see project.py), then the
rules run per file with ``ctx.project`` carrying the cross-module
view — so a rule can ask "does this loop call something that host-
syncs two modules away" instead of guessing from one AST.
"""
from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import pathlib
import sys
from typing import Iterable, Iterator

from .project import ProjectContext, dotted  # noqa: F401  (dotted is
# re-exported: rules and external callers import it from here)

#: directories never scanned: caches, VCS internals, and the fixture
#: corpus (known-bad snippets exist to be red — scanning them would
#: make the tree permanently red)
SKIP_PARTS = {"__pycache__", ".git", "fixtures", ".eggs", "build"}

BASELINE_NAME = ".cephck-baseline.json"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    rule: str
    path: str          # repo-root-relative posix path
    line: int
    symbol: str        # enclosing def/class qualname (or flagged name)
    message: str

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}: {self.rule}{sym}: {self.message}"


class FileContext:
    """One parsed source file plus the cross-file engine options."""

    def __init__(self, path: pathlib.Path, rel: str, source: str,
                 tree: ast.Module, options: dict,
                 project: ProjectContext | None = None):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.options = options
        #: the cross-module pass (set by the engine before rules run)
        self.project = project
        self._parents: dict[ast.AST, ast.AST] | None = None

    def module(self):
        """This file's ModuleInfo in the project pass (import aliases,
        jit registry) — None only if the engine skipped phase 1."""
        return self.project.module_for(self.rel) if self.project else None

    # -- helpers shared by rules ---------------------------------------

    def parents(self) -> dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def qualname(self, node: ast.AST) -> str:
        """Enclosing class/function qualname for a node (best effort)."""
        parts: list[str] = []
        parents = self.parents()
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = parents.get(cur)
        return ".".join(reversed(parts)) or "<module>"

    def imports_jax(self) -> bool:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                if any(a.name == "jax" or a.name.startswith("jax.")
                       for a in node.names):
                    return True
            elif isinstance(node, ast.ImportFrom):
                if node.module and (node.module == "jax" or
                                    node.module.startswith("jax.")):
                    return True
        return False

    def finding(self, rule: str, node: ast.AST, message: str,
                symbol: str | None = None) -> Finding:
        return Finding(rule=rule, path=self.rel,
                       line=getattr(node, "lineno", 0),
                       symbol=symbol if symbol is not None
                       else self.qualname(node),
                       message=message)

    def inline_ignored(self, f: Finding) -> bool:
        """``# cephck: ignore[rule]`` on the finding's line (or the
        line directly above) waives it — for one-off sites where a
        baseline entry would outlive the code it excuses."""
        marker = f"cephck: ignore[{f.rule}]"
        for ln in (f.line - 1, f.line - 2):
            if 0 <= ln < len(self.lines) and marker in self.lines[ln]:
                return True
        return False


def repo_root(start: pathlib.Path | None = None) -> pathlib.Path:
    """Nearest ancestor carrying pyproject.toml (falls back to cwd)."""
    cur = (start or pathlib.Path.cwd()).resolve()
    if cur.is_file():
        cur = cur.parent
    for cand in (cur, *cur.parents):
        if (cand / "pyproject.toml").exists():
            return cand
    return pathlib.Path.cwd()


def collect_files(paths: Iterable[str],
                  root: pathlib.Path) -> list[pathlib.Path]:
    out: list[pathlib.Path] = []
    for p in paths:
        pp = pathlib.Path(p)
        if not pp.is_absolute():
            pp = root / pp
        if pp.is_file() and pp.suffix == ".py":
            out.append(pp)
        elif pp.is_dir():
            for f in sorted(pp.rglob("*.py")):
                if not SKIP_PARTS.intersection(f.parts):
                    out.append(f)
        elif not pp.exists():
            raise FileNotFoundError(f"cephck: no such path: {p}")
    return out


# ------------------------------------------------------------ baseline

class BaselineError(ValueError):
    """Malformed baseline — including any entry without a reason."""


@dataclasses.dataclass
class Suppression:
    rule: str
    path: str
    symbol: str        # "" matches any symbol
    reason: str
    used: int = 0

    def matches(self, f: Finding) -> bool:
        # exact repo-relative path only: a suffix match would let a
        # root "bench.py" entry silently swallow findings from any
        # future tests/bench.py too
        if self.rule != f.rule or f.path != self.path:
            return False
        return self.symbol in ("", f.symbol)


def load_baseline(path: pathlib.Path) -> list[Suppression]:
    """Load and VALIDATE the baseline: every entry must name a rule,
    a path, and a one-line human reason.  An unexplained suppression
    is rejected outright — the baseline is the audit trail for every
    finding the tree is allowed to keep."""
    try:
        data = json.loads(path.read_text())
    except json.JSONDecodeError as ex:
        raise BaselineError(f"{path}: invalid JSON: {ex}") from ex
    entries = data.get("suppressions")
    if not isinstance(entries, list):
        raise BaselineError(f"{path}: expected a 'suppressions' list")
    out = []
    for i, e in enumerate(entries):
        reason = str(e.get("reason", "")).strip()
        rule = str(e.get("rule", "")).strip()
        rel = str(e.get("path", "")).strip()
        if not rule or not rel:
            raise BaselineError(
                f"{path}: suppression #{i} needs 'rule' and 'path'")
        if not reason or "\n" in reason:
            raise BaselineError(
                f"{path}: suppression #{i} ({rule} @ {rel}) needs a "
                "one-line 'reason' — unexplained baseline entries are "
                "not allowed")
        out.append(Suppression(rule=rule, path=rel,
                               symbol=str(e.get("symbol", "")).strip(),
                               reason=reason))
    return out


def prune_baseline(path: pathlib.Path,
                   stale: list[Suppression]) -> int:
    """Rewrite the baseline file dropping `stale` entries (matched by
    rule/path/symbol), preserving everything else verbatim — the
    ``--prune-baseline`` rewrite.  Returns how many entries went."""
    data = json.loads(path.read_text())
    gone = {(s.rule, s.path, s.symbol) for s in stale}
    kept = [e for e in data.get("suppressions", [])
            if (str(e.get("rule", "")).strip(),
                str(e.get("path", "")).strip(),
                str(e.get("symbol", "")).strip()) not in gone]
    dropped = len(data.get("suppressions", [])) - len(kept)
    data["suppressions"] = kept
    path.write_text(json.dumps(data, indent=1) + "\n")
    return dropped


# -------------------------------------------------------------- engine

class Engine:
    def __init__(self, rules, root: pathlib.Path,
                 wire_schema: pathlib.Path | None = None,
                 suppressions: list[Suppression] | None = None):
        self.rules = list(rules)
        self.root = root
        self.options = {
            "wire_schema": wire_schema or
            root / "tests" / "fixtures" / "wire_schema.json",
        }
        self.suppressions = suppressions or []
        self.findings: list[Finding] = []
        self.suppressed: list[tuple[Finding, Suppression]] = []
        self.errors: list[str] = []
        self.scanned: list[str] = []

    def _parse(self, path: pathlib.Path) -> FileContext | None:
        try:
            source = path.read_text()
            tree = ast.parse(source, filename=str(path))
        except (OSError, SyntaxError) as ex:
            self.errors.append(f"{path}: {ex}")
            return None
        try:
            rel = path.resolve().relative_to(self.root).as_posix()
        except ValueError:
            rel = path.as_posix()
        self.scanned.append(rel)
        return FileContext(path, rel, source, tree, self.options)

    def _check_ctx(self, ctx: FileContext) -> Iterator[Finding]:
        for rule in self.rules:
            for f in rule.check(ctx):
                if ctx.inline_ignored(f):
                    continue
                for s in self.suppressions:
                    if s.matches(f):
                        s.used += 1
                        self.suppressed.append((f, s))
                        break
                else:
                    self.findings.append(f)
                    yield f

    def check_file(self, path: pathlib.Path) -> Iterator[Finding]:
        """Single-file scan (fixture tests): the project pass degrades
        to a one-module table, so cross-module rules still run."""
        ctx = self._parse(path)
        if ctx is None:
            return
        project = ProjectContext()
        project.add(ctx.rel, ctx.tree)
        project.finalize()
        ctx.project = project
        yield from self._check_ctx(ctx)

    def run(self, paths: Iterable[str]) -> int:
        # phase 1: parse everything, build the cross-module context
        ctxs: list[FileContext] = []
        project = ProjectContext()
        for f in collect_files(paths, self.root):
            ctx = self._parse(f)
            if ctx is not None:
                project.add(ctx.rel, ctx.tree)
                ctxs.append(ctx)
        project.finalize()
        # phase 2: rules, per file, with the project view attached
        for ctx in ctxs:
            ctx.project = project
            for _ in self._check_ctx(ctx):
                pass
        return 1 if (self.findings or self.errors) else 0

    def stale_suppressions(self) -> list[Suppression]:
        """Unused entries whose path was actually scanned — a partial
        scan (one file) must not cry stale about the rest of the
        baseline."""
        return [s for s in self.suppressions
                if not s.used and s.path in self.scanned]


# ----------------------------------------------------------------- CLI

def sarif_report(rules, findings, errors=(), stale=()) -> dict:
    """SARIF 2.1.0 log for code-scanning uploads (the github format
    annotates the diff; SARIF populates the Security/Code-scanning
    tab and survives as an artifact).  String escaping is json.dumps's
    job — messages with quotes, newlines or %-sequences must round-
    trip verbatim (asserted by tests/test_cephck.py)."""
    fired = {f.rule for f in findings}
    driver_rules = [{
        "id": r.id,
        "shortDescription": {
            "text": (r.doc or r.id).strip().splitlines()[0]},
        "fullDescription": {"text": (r.doc or r.id).strip()},
    } for r in rules if r.id in fired]
    index = {dr["id"]: i for i, dr in enumerate(driver_rules)}
    results = [{
        "ruleId": f.rule,
        "ruleIndex": index[f.rule],
        "level": "error",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": f.line},
            },
        }],
    } for f in findings]
    notifications = [
        {"level": "error", "message": {"text": e}} for e in errors
    ] + [
        {"level": "error",
         "message": {"text": f"stale suppression ({s.rule} @ {s.path})"
                             f" no longer matches any finding"}}
        for s in stale
    ]
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "cephck",
                "rules": driver_rules,
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "invocations": [{
                "executionSuccessful": not (errors or stale),
                "toolExecutionNotifications": notifications,
            }],
            "results": results,
        }],
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ceph_tpu.analysis",
        description="cephck: project-specific static analysis "
                    "(exit 0 = clean, 1 = findings, 2 = bad config)")
    ap.add_argument("paths", nargs="*",
                    default=["ceph_tpu", "tests", "scripts", "bench.py"],
                    help="files/dirs to scan (default: the whole tree)")
    ap.add_argument("--baseline", default=None,
                    help=f"suppression baseline (default: "
                         f"<repo-root>/{BASELINE_NAME})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report everything")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="rewrite the baseline dropping stale entries "
                         "(file/rule pairs that no longer produce a "
                         "finding); without this flag stale entries "
                         "FAIL the run — the blindfold only shrinks")
    ap.add_argument("--wire-schema", default=None,
                    help="wire schema lockfile (default: "
                         "tests/fixtures/wire_schema.json)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout "
                         "(alias for --format json)")
    ap.add_argument("--format", default=None, dest="fmt",
                    choices=("text", "json", "github", "sarif"),
                    help="findings output: text (default), json "
                         "(one machine-readable document), github "
                         "(::error workflow annotations for CI), or "
                         "sarif (2.1.0 log for code-scanning uploads)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print every rule id + one-line summary")
    ap.add_argument("--explain", metavar="RULE",
                    help="print a rule's full doc (how to read and "
                         "fix its findings)")
    args = ap.parse_args(argv)

    from .rules import ALL_RULES
    rules = [cls() for cls in ALL_RULES]

    if args.list_rules:
        for r in rules:
            first = (r.doc or "").strip().splitlines()[0]
            print(f"{r.id:22s} {first}")
        return 0
    if args.explain:
        for r in rules:
            if r.id == args.explain:
                print(f"{r.id}\n{'=' * len(r.id)}\n{r.doc.strip()}")
                return 0
        print(f"cephck: unknown rule {args.explain!r}", file=sys.stderr)
        return 2

    root = repo_root()
    suppressions: list[Suppression] = []
    bpath = None
    if not args.no_baseline:
        bpath = pathlib.Path(args.baseline) if args.baseline \
            else root / BASELINE_NAME
        if bpath.exists():
            try:
                suppressions = load_baseline(bpath)
            except BaselineError as ex:
                print(f"cephck: {ex}", file=sys.stderr)
                return 2
        elif args.baseline:
            print(f"cephck: baseline not found: {bpath}", file=sys.stderr)
            return 2

    wire = pathlib.Path(args.wire_schema) if args.wire_schema else None
    eng = Engine(rules, root, wire_schema=wire, suppressions=suppressions)
    try:
        rc = eng.run(args.paths)
    except FileNotFoundError as ex:
        print(ex, file=sys.stderr)
        return 2

    stale = eng.stale_suppressions()
    if stale and args.prune_baseline and bpath and bpath.exists():
        prune_baseline(bpath, stale)
        for s in stale:
            print(f"cephck: pruned stale suppression "
                  f"({s.rule} @ {s.path})", file=sys.stderr)
        stale = []

    fmt = args.fmt or ("json" if args.as_json else "text")
    if fmt == "json":
        print(json.dumps({
            "findings": [dataclasses.asdict(f) for f in eng.findings],
            "suppressed": len(eng.suppressed),
            "stale": [dataclasses.asdict(s) for s in stale],
            "errors": eng.errors,
        }, indent=1))
    elif fmt == "sarif":
        print(json.dumps(sarif_report(rules, eng.findings,
                                      eng.errors, stale), indent=1))
    elif fmt == "github":
        # GitHub Actions workflow commands: each finding becomes an
        # inline annotation on the PR diff.  Newlines/percent must be
        # URL-style escaped per the workflow-command grammar.
        def esc(s: str) -> str:
            return s.replace("%", "%25").replace("\r", "%0D") \
                    .replace("\n", "%0A")
        for f in eng.findings:
            print(f"::error file={f.path},line={f.line},"
                  f"title=cephck {f.rule}::{esc(f.message)}")
        for e in eng.errors:
            print(f"::error title=cephck parse error::{esc(e)}")
        for s in stale:
            print(f"::error file={s.path},title=cephck stale "
                  f"suppression::{esc(s.rule)} no longer matches any "
                  f"finding — remove it or run --prune-baseline")
        print(f"cephck: {len(eng.findings)} finding(s), "
              f"{len(eng.suppressed)} suppressed by baseline",
              file=sys.stderr)
    else:
        for f in eng.findings:
            print(f.render())
        for e in eng.errors:
            print(f"cephck: parse error: {e}", file=sys.stderr)
        for s in stale:
            print(f"cephck: stale suppression ({s.rule} @ {s.path}) "
                  f"no longer matches any finding — remove it or run "
                  f"--prune-baseline", file=sys.stderr)
        n = len(eng.findings)
        print(f"cephck: {n} finding(s), {len(eng.suppressed)} "
              f"suppressed by baseline"
              + (f", {len(eng.errors)} parse error(s)"
                 if eng.errors else "")
              + (f", {len(stale)} STALE suppression(s)"
                 if stale else ""))
    if stale and rc == 0:
        # a suppression nothing matches is a blindfold over code that
        # moved: the gate fails until the baseline shrinks to fit
        rc = 1
    return rc
