"""ChaosRunner: schedule-driven chaos against a MiniCluster.

Where the thrashers (thrasher.py) draw random action sequences, a
chaos schedule is *declarative*: a list of timed events — partitions,
link-loss, delay, daemon kills — applied at simulated-time offsets
while client IO runs, with cluster invariants checked at heal points
and at the end (ref: the qa netem/iptables tasks + ceph_manager's
wait_for_clean/wait_for_health verification loops, collapsed into one
harness over the FaultPlane).

A schedule is a list of dicts::

    [{"at": 10.0, "action": "partition", "a": ["mon.2"],
      "b": ["mon.0", "mon.1"], "label": "minority"},
     {"at": 40.0, "action": "heal", "target": "minority"},
     {"at": 55.0, "action": "check"}]

``at`` is seconds after the runner's sim-time start.  Every fault
event's installed rule ids are remembered under its ``label`` (or its
schedule index) so a later ``heal`` can lift exactly that fault.

Invariants (checked by ``check_invariants``): a majority quorum with
a leader re-forms; every PG settles active+clean with nothing
recovering; every *acked* write reads back byte-identical; SLOW_OPS
and health degradation clear; the crash table stays empty; RGW
multisite sync lag drains (when gateways exist).  Violations raise
``InvariantViolation`` carrying the fault log tail for replay — the
run is reproducible from (cluster fault_seed, schedule).
"""
from __future__ import annotations

import random
import time as _time

from ..common.options import global_config
from .cluster import MiniCluster


class InvariantViolation(AssertionError):
    """A cluster invariant failed after (or during) a chaos run."""


def percentile(samples: list[float], q: float) -> float:
    """Nearest-rank percentile; 0.0 on an empty sample set."""
    if not samples:
        return 0.0
    s = sorted(samples)
    i = min(len(s) - 1, max(0, int(round(q / 100.0 * len(s))) - 1))
    return s[i]


class ChaosRunner:
    """Execute one declarative chaos schedule under live client IO."""

    #: actions that install FaultPlane rules (tracked for heal)
    FAULT_ACTIONS = ("partition", "isolate", "isolate_primary",
                     "drop", "delay", "dup", "reorder")

    def __init__(self, cluster: MiniCluster, schedule: list[dict],
                 rados=None, pool: str = "chaos", seed: int = 0,
                 start: float = 50_000.0, io_per_step: int = 2,
                 strict_health: bool = True):
        self.c = cluster
        self.plane = cluster.network.faults
        self.schedule = sorted(
            [dict(e) for e in schedule], key=lambda e: e["at"])
        self.rng = random.Random(f"chaos|{seed}")
        self.start = start
        self.now = start
        self.io_per_step = io_per_step
        self.strict_health = strict_health
        self.r = rados if rados is not None else cluster.rados()
        from ..client import RadosError
        try:
            self.r.pool_lookup(pool)
        except RadosError:
            self.r.pool_create(pool, pg_num=16)
            if not cluster.threaded:
                cluster.pump()
        self.io = self.r.open_ioctx(pool)
        #: every write ever issued: oid -> (data, fut, t0, phase)
        self._writes: dict[str, tuple] = {}
        self._oid_seq = 0
        #: phase label -> completed-op latency samples (seconds)
        self.phase_lats: dict[str, list[float]] = {}
        self._phase = "pre"
        #: label -> FaultPlane rule ids (for targeted heals)
        self._installed: dict[str, list[int]] = {}
        #: OSDs the schedule killed and has not revived
        self._downed: set[int] = set()
        self.log: list[str] = []

    # ------------------------------------------------------------ time
    def _tick_to(self, offset: float) -> None:
        """Advance sim time to `start + offset` (schedule times are
        offsets from the run start) in sub-grace steps — the thrasher
        cadence: failure detection sees production-like intervals —
        interleaving client IO and completion harvesting."""
        grace = global_config()["osd_heartbeat_grace"]
        step = grace / 2 + 1
        target = max(self.start + offset, self.now)
        while self.now < target:
            self.now = min(target, self.now + step)
            self.c.tick(self.now)
            self._issue_io()
            self._harvest()

    def _settle(self, rounds: int = 4) -> None:
        """Post-event propagation: ticks with drains, no new IO."""
        for _ in range(rounds):
            self.now += global_config()["osd_heartbeat_grace"] / 2 + 1
            self.c.tick(self.now)
            self._harvest()

    # -------------------------------------------------------------- io
    def _issue_io(self) -> None:
        for _ in range(self.io_per_step):
            self._oid_seq += 1
            oid = f"chaos_{self._oid_seq:05d}"
            data = bytes([self.rng.randrange(256)]) \
                * self.rng.randrange(1, 800)
            fut = self.io.aio_write_full(oid, data)
            self._writes[oid] = (data, fut, _time.monotonic(),
                                 self._phase)
        if not self.c.threaded:
            self.c.pump()

    def _harvest(self) -> None:
        """Record first-observed completion latencies per phase."""
        for oid, (data, fut, t0, phase) in self._writes.items():
            if t0 is None or not fut.done():
                continue
            self.phase_lats.setdefault(phase, []).append(
                _time.monotonic() - t0)
            self._writes[oid] = (data, fut, None, phase)

    def acked_writes(self) -> dict[str, bytes]:
        """oid -> data for every write the cluster acknowledged OK.
        These are the durability contract: they MUST read back."""
        return {oid: data
                for oid, (data, fut, _t0, _ph) in self._writes.items()
                if fut.done() and fut.result == 0}

    def _drain_io(self, timeout: float = 30.0) -> None:
        """Wait for every in-flight write to complete (parked ops
        resend via the rescan timer, which is real-time)."""
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            if not self.c.threaded:
                self.c.pump()
            else:
                self.plane.flush()
            self._harvest()
            if all(f.done()
                   for _d, f, _t, _p in self._writes.values()):
                return
            _time.sleep(0.02)
        undone = [o for o, (_d, f, _t, _p) in self._writes.items()
                  if not f.done()]
        raise InvariantViolation(
            f"writes never completed after heal: {undone[:8]} "
            f"(+{max(0, len(undone) - 8)} more); log: {self.log}")

    # ---------------------------------------------------------- events
    def _apply(self, ev: dict, idx: int) -> None:
        act = ev["action"]
        label = ev.get("label", f"ev{idx}")
        self._phase = label if act in self.FAULT_ACTIONS else self._phase
        self.log.append(f"t={ev['at']:.1f} {act} [{label}]")
        if act == "partition":
            ids = self.plane.partition(
                ev["a"], ev["b"],
                symmetric=ev.get("symmetric", True))
            self._installed.setdefault(label, []).extend(ids)
        elif act == "isolate":
            ids = self.plane.isolate(ev["entity"])
            self._installed.setdefault(label, []).extend(ids)
        elif act == "isolate_primary":
            osd = self._primary_of(ev["oid"], ev.get("pool"))
            self.log.append(f"  -> primary is osd.{osd}")
            ids = self.plane.isolate(f"osd.{osd}")
            self._installed.setdefault(label, []).extend(ids)
        elif act in ("drop", "delay", "dup", "reorder"):
            kw = {k: ev[k] for k in ("drop", "delay", "jitter", "dup",
                                     "reorder", "reset", "types")
                  if k in ev}
            if act == "drop" and "drop" not in kw:
                kw["drop"] = ev["p"]
            rid = self.plane.add_rule(ev["src"], ev["dst"], **kw)
            self._installed.setdefault(label, []).append(rid)
        elif act == "kill_osd":
            self.c.kill_osd(ev["osd"])
            self._downed.add(ev["osd"])
        elif act == "revive_osd":
            self.c.revive_osd(ev["osd"])
            self._downed.discard(ev["osd"])
            if not self.c.threaded:
                self.c.pump()
        elif act == "heal":
            target = ev.get("target")
            if target is None:
                self.plane.heal()
                self._installed.clear()
            else:
                self.plane.heal(self._installed.pop(target, []))
            self._phase = f"healed:{target or 'all'}"
        elif act == "check":
            self._settle()
            self.check_invariants(
                final=False, strict_health=ev.get("strict", False))
        else:
            raise ValueError(f"unknown chaos action {act!r}")

    def _primary_of(self, oid: str, pool: str | None) -> int:
        pid = self.r.pool_lookup(pool) if pool else self.io.pool_id
        m = self.c.mon.osdmap
        raw = m.object_locator_to_pg(oid, pid)
        _up, _upp, _acting, primary = m.pg_to_up_acting_osds(raw)
        return primary

    # -------------------------------------------------------------- run
    def run(self) -> dict:
        """Execute the schedule, heal anything still broken, drain IO,
        check every invariant, and return the report."""
        self._issue_io()
        for i, ev in enumerate(self.schedule):
            self._tick_to(ev["at"])
            self._apply(ev, i)
        # terminal heal: whatever the schedule left broken comes back
        self._phase = "final"
        if self.plane.rules():
            self.plane.heal()
            self._installed.clear()
            self.log.append("final heal (leftover rules)")
        for osd in sorted(self._downed):
            self.c.revive_osd(osd)
            self.log.append(f"final revive osd.{osd}")
        self._downed.clear()
        if not self.c.threaded:
            self.c.pump()
        self._settle()
        self._drain_io()
        self.check_invariants(final=True,
                              strict_health=self.strict_health)
        return self.report()

    # ------------------------------------------------------ invariants
    def _leader(self):
        for _ in range(40):
            ldr = self.c.leader()
            if ldr is not None:
                return ldr
            self._settle(1)
        raise InvariantViolation(
            f"no mon leader re-elected; log: {self.log}")

    def check_invariants(self, final: bool = True,
                         strict_health: bool | None = None) -> None:
        if strict_health is None:
            strict_health = self.strict_health
        ldr = self._leader()
        rc, _, q = ldr.handle_command({"prefix": "quorum_status"})
        assert rc == 0
        if len(q["quorum"]) * 2 <= len(q["mons"]) or \
                q["leader"] not in q["quorum"]:
            raise InvariantViolation(
                f"quorum never re-formed: {q}; log: {self.log}")
        # PGs settle active+clean (recovery may still be running —
        # keep ticking within a bounded budget)
        for attempt in range(60):
            if not self.c.threaded:
                self.c.pump()
            recovering = sum(d.pgs_recovering()
                             for d in self.c.osds.values())
            rc, _, pg = ldr.handle_command({"prefix": "pg stat"})
            states = pg["states"]
            dirty = {s: n for s, n in states.items()
                     if "clean" not in s or "active" not in s}
            if not recovering and not dirty:
                break
            self._settle(1)
            if self.c.threaded:
                _time.sleep(0.02)
        else:
            raise InvariantViolation(
                f"PGs never went active+clean: recovering="
                f"{recovering} states={states}; log: {self.log}")
        # acked writes are durable, byte-identical
        if final:
            self._drain_io()
        bad = []
        for oid, data in sorted(self.acked_writes().items()):
            got = self.io.read(oid)
            if got != data:
                bad.append((oid, len(data), len(got)))
        if bad:
            raise InvariantViolation(
                f"acked writes corrupted: {bad[:5]}; log: {self.log}")
        # health clears: SLOW_OPS always; full HEALTH_OK when strict
        for attempt in range(40):
            rc, status, h = ldr.handle_command({"prefix": "health"})
            checks = h["checks"]
            if "SLOW_OPS" not in checks and \
                    (not strict_health or status == "HEALTH_OK"):
                break
            self._settle(1)
            if self.c.threaded:
                _time.sleep(0.02)
        else:
            raise InvariantViolation(
                f"health never cleared: {status} {checks}; "
                f"log: {self.log}")
        # crash table: chaos must not have crashed any daemon
        rc, _, crashes = ldr.handle_command({"prefix": "crash ls"})
        if crashes:
            raise InvariantViolation(
                f"crash table not empty: "
                f"{[c.get('crash_id') for c in crashes]}; "
                f"log: {self.log}")
        # RGW multisite: sync lag drains after heal
        for gw in getattr(self.c, "rgws", []):
            deadline = _time.monotonic() + 30.0
            while not gw.sync.caught_up():
                if _time.monotonic() > deadline:
                    raise InvariantViolation(
                        f"rgw zone {gw.zone} sync lag never drained: "
                        f"{gw.sync.status()}; log: {self.log}")
                _time.sleep(0.05)

    # ----------------------------------------------------------- report
    def report(self) -> dict:
        """Per-phase op latency percentiles + the fault fingerprint."""
        phases = []
        for label, lats in self.phase_lats.items():
            phases.append({
                "phase": label, "ops": len(lats),
                "p50_ms": round(percentile(lats, 50) * 1e3, 3),
                "p99_ms": round(percentile(lats, 99) * 1e3, 3)})
        return {
            "phases": phases,
            "ops_total": sum(len(v) for v in self.phase_lats.values()),
            "acked": len(self.acked_writes()),
            "fault_digest": self.plane.digest(),
            "fault_counts": dict(self.plane.counts),
            "events": list(self.log),
        }
