"""crc32c (Castagnoli) with the reference's raw-seed chaining semantics.

`crc32c(seed, data)` behaves like the reference's `ceph_crc32c(seed,
buf, len)` (behavioral ref: src/common/crc32c.h, table impl
src/common/sctp_crc32.c): the seed is the running crc — no implicit
pre/post inversion — so cumulative shard hashes (ECUtil HashInfo) chain
calls directly.  Validated against the reference's published vectors
(src/test/common/test_crc32c.cc:18-45).

Fast path: the native slice-by-8 C library (native/crc32c.c), compiled
on demand with the system compiler and cached next to the package.
Fallback: a numpy table walk (correct, slower) so the package works
without a toolchain.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading

from .lockdep import make_lock

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "crc32c.c")
_LIB_DIR = os.path.join(_REPO_ROOT, "ceph_tpu", "_native")
_LIB = os.path.join(_LIB_DIR, "libceph_tpu_native.so")

_lock = make_lock("crc32c.native")
_native = None
_native_tried = False


def _build_native() -> str | None:
    if not os.path.exists(_SRC):
        return None
    os.makedirs(_LIB_DIR, exist_ok=True)
    if (os.path.exists(_LIB)
            and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
        return _LIB
    # compile to a temp name + atomic rename so a concurrent process
    # never dlopens a half-written .so
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    for cc in ("cc", "gcc", "clang"):
        try:
            subprocess.run(
                [cc, "-O3", "-shared", "-fPIC", _SRC, "-o", tmp],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, _LIB)
            return _LIB
        except (OSError, subprocess.SubprocessError):
            continue
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
    return None


def _load_native():
    global _native, _native_tried
    if _native_tried:
        return _native
    with _lock:
        if _native_tried:
            return _native
        try:
            path = _build_native()
            if path is not None:
                lib = ctypes.CDLL(path)
                fn = lib.ceph_tpu_crc32c
                fn.restype = ctypes.c_uint32
                fn.argtypes = [ctypes.c_uint32, ctypes.c_char_p,
                               ctypes.c_size_t]
                _native = fn
        except OSError:
            _native = None
        _native_tried = True
    return _native


def _make_table() -> np.ndarray:
    poly = np.uint64(0x82F63B78)
    tbl = np.zeros(256, dtype=np.uint64)
    for i in range(256):
        c = np.uint64(i)
        for _ in range(8):
            c = (c >> np.uint64(1)) ^ poly if c & np.uint64(1) \
                else c >> np.uint64(1)
        tbl[i] = c
    return tbl.astype(np.uint32)


_TABLE = _make_table()


def _crc32c_py(seed: int, data: bytes) -> int:
    crc = seed & 0xFFFFFFFF
    tbl = _TABLE
    for b in data:
        crc = int(tbl[(crc ^ b) & 0xFF]) ^ (crc >> 8)
    return crc


def crc32c(seed: int, data) -> int:
    """Running crc32c over data; chain by passing the previous result
    as the next seed.  data: bytes-like or uint8 ndarray."""
    if isinstance(data, np.ndarray):
        data = data.tobytes()
    elif isinstance(data, (bytearray, memoryview)):
        data = bytes(data)
    fn = _load_native()
    if fn is not None:
        return fn(seed & 0xFFFFFFFF, data, len(data))
    return _crc32c_py(seed, data)
