"""repairc: the repair-schedule compiler.

Lowers a plugin's repair plan for one concrete erasure *signature*
(code, failed-shard set, survivor set, per-helper sub-chunk extents)
into a single fused repair *program*: gather the survivor planes into
one dense array, run one grouped GF(2^8) matmul against a
probe-derived repair matrix, scatter the rebuilt shard streams back
out.  Programs are cached per signature in a cost-weighted LRU
(`RepairProgramCache`, generalizing the decode-*matrix* cache of
ceph_tpu/ec/matrix_code.py to repair-*programs*), so steady-state
recovery never re-derives or re-compiles the schedule.

Plugins contribute plans through the `repair_schedule(erasures,
available)` interface hook (ceph_tpu/ec/interface.py); `None` means
"no partial plan for this signature" and callers fall back to
wholesale full-chunk recovery.

Motivated by schedule-level XOR program compilation (arxiv
2108.02692) and the LRC rebuild-time results of arxiv 1906.08602.
"""
from .plan import RepairPlan
from .compiler import RepairProgram, compile_program, interpret_plan
from .cache import RepairProgramCache, program_for, cache_of

__all__ = ["RepairPlan", "RepairProgram", "RepairProgramCache",
           "compile_program", "interpret_plan", "program_for",
           "cache_of"]
