"""Observability subsystem: daemon crash capture -> mon crash table ->
RECENT_CRASH health -> anonymized telemetry -> windowed insights
(ref: src/pybind/mgr/crash/, telemetry/, insights/).

Acceptance (ISSUE 4): killing an OSD with an injected fault produces a
`crash ls` entry with a real backtrace, `ceph health` shows
RECENT_CRASH, `crash archive-all` clears it, and `telemetry show`
returns an anonymized report including the crash summary — with
exactly ONE report per crash even when the spool and the live post
both deliver it."""
import io as iomod
import json
import os
import time

import pytest

from ceph_tpu.common.crash import (CrashReporter, crash_meta,
                                   sanitize_backtrace)
from ceph_tpu.msg.messenger import LocalNetwork
from ceph_tpu.mon.monitor import Monitor, build_initial
from ceph_tpu.testing import MiniCluster


def _boom():
    raise ValueError("synthetic fault for crash capture")


def _exc():
    try:
        _boom()
    except ValueError as ex:
        return ex


# ------------------------------------------------------ capture library

def test_crash_meta_fields():
    meta = crash_meta("osd.7", _exc(), stamp=1_700_000_000.25)
    assert meta["crash_id"].endswith("_osd.7")
    assert meta["crash_id"].startswith(meta["timestamp"])
    assert meta["entity_name"] == "osd.7"
    assert meta["entity_type"] == "osd"
    assert meta["exc_type"] == "ValueError"
    assert "synthetic fault" in meta["exc_msg"]
    # a REAL backtrace: the raising frame is in there
    assert any("_boom" in ln for ln in meta["backtrace"])
    assert meta["archived"] is None
    assert meta["stamp"] == 1_700_000_000.25
    assert "Z" in meta["timestamp"]


def test_sanitize_backtrace_strips_paths():
    meta = crash_meta("osd.1", _exc())
    clean = sanitize_backtrace(meta["backtrace"])
    assert any("test_crash_telemetry.py" in ln for ln in clean)
    assert not any("/" in ln or "\\" in ln
                   for ln in clean if 'File "' in ln), clean
    # the final traceback line is the exception MESSAGE — OSError et
    # al. embed the offending path there, and telemetry ships the
    # whole backtrace: the dir prefix must go
    try:
        open("/var/lib/ceph-tpu-nope/osd.3/store")
    except OSError as ex:
        leaky = ex
    clean = sanitize_backtrace(crash_meta("osd.3", leaky)["backtrace"])
    assert not any("/var/lib" in ln for ln in clean), clean
    assert any("'store'" in ln for ln in clean), clean


def test_reporter_spool_drain_lifecycle(tmp_path):
    posted = []
    rep = CrashReporter("osd.3", crash_dir=str(tmp_path / "crash"),
                        post=posted.append)
    meta = rep.capture(_exc())
    assert posted == [meta]
    # spooled under <crash_dir>/<safe id>/meta.json
    assert rep.spooled() == [meta]
    spool_files = list((tmp_path / "crash").rglob("meta.json"))
    assert len(spool_files) == 1
    # next-boot drain re-posts; the file stays until the ack
    assert rep.drain() == 1
    assert len(posted) == 2
    rep.mark_delivered(meta["crash_id"])
    assert rep.spooled() == []
    assert rep.drain() == 0


def test_reporter_throttles_repeat_signature():
    """A persistently failing survive-loop tick must not storm the
    crash table: identical signatures inside the window are dropped."""
    posted = []
    rep = CrashReporter("osd.0", post=posted.append)
    assert rep.capture(_exc())
    assert rep.capture(_exc()) == {}
    assert len(posted) == 1
    # a DIFFERENT exception captures immediately
    assert rep.capture(RuntimeError("other fault"))
    assert len(posted) == 2


# ----------------------------------------------------- mon crash table

def make_mon():
    net = LocalNetwork()
    m, w = build_initial(4)
    mon = Monitor(net, initial_map=m, initial_wrapper=w, threaded=False)
    mon.init()
    return mon


def test_crash_service_dedup_archive_prune():
    mon = make_mon()
    meta = crash_meta("osd.2", _exc(), stamp=time.time())
    for _ in range(2):   # spool+post double delivery
        rc, outs, _ = mon.handle_command(
            {"prefix": "crash post", "meta": meta})
        assert rc == 0
    rc, outs, crashes = mon.handle_command({"prefix": "crash ls"})
    assert rc == 0 and len(crashes) == 1
    assert crashes[0]["crash_id"] == meta["crash_id"]
    rc, _, stat = mon.handle_command({"prefix": "crash stat"})
    assert stat == {"total": 1, "new": 1}
    # info round-trips the full meta
    rc, _, info = mon.handle_command(
        {"prefix": "crash info", "id": meta["crash_id"]})
    assert rc == 0 and info["backtrace"] == meta["backtrace"]
    rc, outs, _ = mon.handle_command(
        {"prefix": "crash info", "id": "nope"})
    assert rc == -2
    # archive one -> ls-new empties, ls still shows it
    rc, _, _ = mon.handle_command(
        {"prefix": "crash archive", "id": meta["crash_id"]})
    assert rc == 0
    rc, _, new = mon.handle_command({"prefix": "crash ls-new"})
    assert new == []
    rc, _, crashes = mon.handle_command({"prefix": "crash ls"})
    assert len(crashes) == 1 and crashes[0]["archived"]
    # prune keep=0 days drops archived reports
    rc, _, _ = mon.handle_command({"prefix": "crash prune", "keep": 0})
    assert rc == 0
    rc, _, crashes = mon.handle_command({"prefix": "crash ls"})
    assert crashes == []
    # malformed post is rejected
    rc, outs, _ = mon.handle_command(
        {"prefix": "crash post", "meta": {"crash_id": "x"}})
    assert rc == -22 and "missing" in outs
    mon.shutdown()


def test_crash_table_survives_mon_restart():
    """The table is a PaxosService: a revived mon still answers
    `crash ls` (the cluster-log persistence property)."""
    c = MiniCluster(n_osd=2, threaded=True)
    try:
        c.wait_all_up()
        c.crash_osd(1)
        r = c.rados()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if r.mon_command({"prefix": "crash ls"})[2]:
                break
            time.sleep(0.05)
        store = c.mon.store
        cid = r.mon_command({"prefix": "crash ls"})[2][0]["crash_id"]
        mon2 = Monitor(LocalNetwork(), store=store, threaded=False)
        mon2.init()
        rc, _, crashes = mon2.handle_command({"prefix": "crash ls"})
        assert rc == 0 and [m["crash_id"] for m in crashes] == [cid]
        mon2.shutdown()
    finally:
        c.shutdown()


# --------------------------------------------------------- e2e cluster

def test_osd_crash_e2e_recent_crash_and_dedup(tmp_path):
    """The acceptance path: OSD under IO + injected fault -> exactly
    one crash report (live post + spool drain on revive), RECENT_CRASH
    raised, archived away, telemetry carries the summary."""
    crash_dir = str(tmp_path / "osd1-crash")
    c = MiniCluster(n_osd=3, threaded=True)
    try:
        c.wait_all_up()
        # osd.1 spools as well as posts
        c.kill_osd(1)
        c.start_osd(1, crash_dir=crash_dir)
        c.wait_all_up()
        r = c.rados()
        r.pool_create("cp", pg_num=8)
        io = r.open_ioctx("cp")
        for i in range(8):
            io.write_full(f"o{i}", b"x" * 64)
        mgr = c.start_mgr()
        mgr.start_crash()
        mgr.start_telemetry()
        mgr.observability_tick()
        rc, _, health = r.mon_command({"prefix": "health"})
        assert "RECENT_CRASH" not in health["checks"]

        c.crash_osd(1)
        assert 1 not in c.osds          # reaped like an aborted process
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            _, _, crashes = r.mon_command({"prefix": "crash ls"})
            if crashes:
                break
            time.sleep(0.05)
        assert len(crashes) == 1, crashes
        meta = crashes[0]
        assert meta["entity_name"] == "osd.1"
        assert "injected crash" in meta["exc_msg"]
        assert any("heartbeat_tick" in ln for ln in meta["backtrace"])
        # the report was spooled before the post (the daemon died
        # before the ack could retire it, or the ack already did —
        # either way the revive below converges the lifecycle)
        # RECENT_CRASH via the mgr module-health merge path
        mgr.observability_tick()
        rc, outs, health = r.mon_command({"prefix": "health"})
        assert health["status"] == "HEALTH_WARN"
        assert "RECENT_CRASH" in health["checks"], health
        rc, _, detail = r.mon_command({"prefix": "health detail"})
        assert any("osd.1 crashed" in d for d in
                   detail["checks"]["RECENT_CRASH"]["detail"])

        # revive with the SAME crash dir: any unacked spool copy
        # drains on boot, the table dedups, and the ack retires the
        # spool file — exactly one report, empty spool, either way
        c.start_osd(1, crash_dir=crash_dir)
        c.wait_all_up()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if not list(tmp_path.rglob("meta.json")):
                break
            time.sleep(0.05)
        assert not list(tmp_path.rglob("meta.json")), \
            "spool copy never retired by the ack"
        _, _, crashes = r.mon_command({"prefix": "crash ls"})
        assert len(crashes) == 1, "spool+post delivered a duplicate"

        # telemetry report includes the crash summary, anonymized
        mgr.observability_tick()
        rc, outs, rep = r.mon_command({"prefix": "telemetry show"})
        assert rc == 0, outs
        assert rep["crash"]["summary"]["total"] == 1
        assert rep["crash"]["reports"][0]["entity_type"] == "osd"

        # archiving clears the health check on the next tick
        rc, _, _ = r.mon_command({"prefix": "crash archive-all"})
        assert rc == 0
        mgr.observability_tick()
        rc, _, health = r.mon_command({"prefix": "health"})
        assert "RECENT_CRASH" not in health["checks"], health

        # prometheus exposes the archive-state gauge
        text = mgr.start_prometheus(port=0).collect()
        assert 'ceph_crash_reports{status="archived"} 1' in text
        assert 'ceph_crash_reports{status="new"} 0' in text
        mgr.prometheus.shutdown()
    finally:
        c.shutdown()


def test_quorum_mons_drain_crash_spool(tmp_path):
    """A QUORUM member's spool drains once the election settles: the
    leader commits its reports locally, a peon forwards them to the
    leader and retires each spool file on the ack (standalone-only
    drain left spools stranded forever on multi-mon deployments)."""
    dirs = {r: str(tmp_path / f"mon{r}-crash") for r in (0, 2)}
    for r in (0, 2):   # rank 0 wins the election; rank 2 stays a peon
        rep = CrashReporter(f"mon.{r}", crash_dir=dirs[r])
        rep.spool(crash_meta(f"mon.{r}", _exc(), stamp=time.time()))
    c = MiniCluster(n_osd=2, n_mon=3, threaded=False,
                    mon_crash_dirs=dirs)
    try:
        for _ in range(10):
            c.pump()
        assert c.mon.is_leader
        rc, _, crashes = c.mon.handle_command({"prefix": "crash ls"})
        assert rc == 0
        assert sorted(m["entity_name"] for m in crashes) == \
            ["mon.0", "mon.2"], crashes
        assert not list(tmp_path.rglob("meta.json")), \
            "spool copies never retired by the commit/ack"
    finally:
        c.shutdown()


def test_mgr_module_exception_still_replies():
    """A module handler that raises an UNEXPECTED exception must still
    answer: without the reply the client spins out its 30s deadline
    and the mon's _mgr_proxy entry for the tid leaks forever."""
    c = MiniCluster(n_osd=2, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        mgr = c.start_mgr()
        mgr.start_telemetry()
        mgr.telemetry.handle_command = lambda cmd: (_ for _ in ()) \
            .throw(AttributeError("broken module"))
        t0 = time.monotonic()
        rc, outs, _ = r.mon_command({"prefix": "telemetry show"})
        assert rc == -5 and "broken module" in outs
        assert time.monotonic() - t0 < 10.0
        assert c.mon._mgr_proxy == {}, "proxy entry leaked"
        # the other module still answers through the same proxy
        ins = mgr.start_insights()
        ins.tick(now=1.0)
        assert r.mon_command({"prefix": "insights"})[0] == 0
    finally:
        c.shutdown()


def test_mds_crash_spool_retired_on_ack(tmp_path):
    """MDS crash posts carry real tids: the mon's ack retires the
    spool copy (tid=0 fire-and-forget left spool dirs growing by one
    per crash forever)."""
    c = MiniCluster(n_osd=2, threaded=True)
    try:
        c.wait_all_up()
        mds = c.start_mds(0, crash_dir=str(tmp_path / "mds-crash"))
        mds.crash_reporter.capture(RuntimeError("mds fault"))
        r = c.rados()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if not list(tmp_path.rglob("meta.json")):
                break
            time.sleep(0.05)
        assert not list(tmp_path.rglob("meta.json")), \
            "MDS spool copy never retired by the ack"
        _, _, crashes = r.mon_command({"prefix": "crash ls"})
        assert [m["entity_name"] for m in crashes] == ["mds.0"]
    finally:
        c.shutdown()


def test_module_health_expires_after_mgr_death():
    """Satellite bugfix: a dead mgr's last `mgr health report` must
    not warn forever — entries are stamped and expire after
    mon_mgr_health_grace (sim-clock driven)."""
    c = MiniCluster(n_osd=2, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        c.tick(1000.0)               # enter the simulated clock domain
        rc, _, _ = r.mon_command({
            "prefix": "mgr health report",
            "checks": {"FAKE_MODULE_WARN": {
                "severity": "HEALTH_WARN", "summary": "module warn",
                "detail": []}}})
        assert rc == 0
        rc, _, health = r.mon_command({"prefix": "health"})
        assert "FAKE_MODULE_WARN" in health["checks"]
        # inside the grace the check persists
        c.tick(1030.0)
        rc, _, health = r.mon_command({"prefix": "health"})
        assert "FAKE_MODULE_WARN" in health["checks"]
        # past mon_mgr_health_grace (60s) with no re-report: expired
        c.tick(1100.0)
        rc, _, health = r.mon_command({"prefix": "health"})
        assert "FAKE_MODULE_WARN" not in health["checks"], health
        # a live mgr re-reporting repopulates within one period
        rc, _, _ = r.mon_command({
            "prefix": "mgr health report",
            "checks": {"FAKE_MODULE_WARN": {
                "severity": "HEALTH_WARN", "summary": "module warn",
                "detail": []}}})
        rc, _, health = r.mon_command({"prefix": "health"})
        assert "FAKE_MODULE_WARN" in health["checks"]
    finally:
        c.shutdown()


def test_health_slices_merge_across_modules():
    """set_health_checks: devicehealth and crash slices coexist in one
    report instead of clobbering each other."""
    c = MiniCluster(n_osd=2, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        mgr = c.start_mgr()
        mgr.start_crash()
        mgr.start_devicehealth()
        # simulated clock so the 5s pg-stat report interval elapses
        c.tick(10.0)
        c.osds[0].store.media_errors = {"csum_errors": 3,
                                        "read_errors": 0}
        c.crash_osd(1, now=20.0)     # stat report + injected fault
        time.sleep(0.2)
        mgr.observability_tick()     # RECENT_CRASH slice
        mgr.devicehealth_tick()      # DEVICE_HEALTH slice
        rc, _, health = r.mon_command({"prefix": "health"})
        assert "RECENT_CRASH" in health["checks"], health
        assert "DEVICE_HEALTH" in health["checks"], health
    finally:
        c.shutdown()


# ----------------------------------------------------------- telemetry

def test_telemetry_anonymized_and_schema_stable():
    c = MiniCluster(n_osd=4, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        r.pool_create("tp", pg_num=8)
        mgr = c.start_mgr()
        mgr.start_crash()
        tm = mgr.start_telemetry()
        c.crash_osd(2)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if r.mon_command({"prefix": "crash ls"})[2]:
                break
            time.sleep(0.05)
        mgr.observability_tick()
        rc, _, rep = r.mon_command({"prefix": "telemetry show"})
        assert rc == 0
        # stable JSON schema: two compiles agree on the key structure
        rep2 = tm.compile_report()
        assert sorted(rep) == sorted(rep2)
        assert sorted(rep["basic"]) == sorted(rep2["basic"])
        js = json.dumps(rep)
        # anonymization contract: hashed id, no hostnames, no raw
        # paths, no entity names, no pool names
        import socket
        host = socket.gethostname()
        assert host not in js
        assert "/" not in js.replace("\\/", ""), js
        assert "osd.2" not in js and "tp" not in \
            json.dumps(rep["basic"])
        assert len(rep["cluster_id"]) == 32
        assert rep["basic"]["osds"]["total"] == 4
        assert rep["basic"]["pools"]["count"] == 1
        assert rep["crash"]["reports"][0]["entity_type"] == "osd"
        assert all('File "' not in ln or "/" not in ln
                   for ln in rep["crash"]["reports"][0]["backtrace"])
        # ident channel is OFF by default; enabling it adds names
        rc, _, st = r.mon_command({"prefix": "telemetry status"})
        assert st["channels"]["ident"] is False
        rc, _, _ = r.mon_command({"prefix": "telemetry channel",
                                  "name": "ident", "enabled": True})
        assert rc == 0
        mgr.observability_tick()
        rc, _, rep = r.mon_command({"prefix": "telemetry show"})
        assert rep["ident"]["mons"] == ["mon.0"]
        # crash channel off removes the section
        rc, _, _ = r.mon_command({"prefix": "telemetry channel",
                                  "name": "crash", "enabled": False})
        mgr.observability_tick()
        rc, _, rep = r.mon_command({"prefix": "telemetry show"})
        assert "crash" not in rep
        # off gates show
        rc, _, _ = r.mon_command({"prefix": "telemetry off"})
        rc, outs, _ = r.mon_command({"prefix": "telemetry show"})
        assert rc == -1 and "telemetry is off" in outs
        rc, _, _ = r.mon_command({"prefix": "telemetry on"})
        mgr.observability_tick()
        assert r.mon_command({"prefix": "telemetry show"})[0] == 0
    finally:
        c.shutdown()


def test_mgr_proxy_without_mgr_is_fast_eagain():
    c = MiniCluster(n_osd=2, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        t0 = time.monotonic()
        rc, outs, _ = r.mon_command({"prefix": "telemetry show"})
        assert rc == -11 and "no active mgr" in outs
        rc, outs, _ = r.mon_command({"prefix": "insights"})
        assert rc == -11
        # "fast" means the short mgr-register grace, not the client's
        # full 30s-per-command EAGAIN retry deadline
        assert time.monotonic() - t0 < 10.0
        # a registered mgr without the module enabled: ENOENT, not hang
        c.start_mgr()
        rc, outs, _ = r.mon_command({"prefix": "telemetry show"})
        assert rc == -2 and "not enabled" in outs
    finally:
        c.shutdown()


# ------------------------------------------------------------ insights

def test_insights_window_math():
    c = MiniCluster(n_osd=2, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        mgr = c.start_mgr()
        mgr.start_crash()
        ins = mgr.start_insights(window=100.0)
        ins.tick(now=1000.0)
        r.pool_create("ip", pg_num=8)   # osdmap epoch bump
        ins.tick(now=1050.0)
        ins.tick(now=1200.0)
        rep = ins.report(now=1200.0)
        # only samples in (1100, 1200] count
        assert rep["health"]["samples"] == 1
        assert rep["window_seconds"] == 100.0
        rep_all = ins.report(now=1050.0)
        assert rep_all["health"]["samples"] == 2
        # epoch delta spans the pool create within the window
        assert rep_all["osdmap"]["epoch_delta"] >= 1
        assert rep_all["osdmap"]["last_epoch"] > \
            rep_all["osdmap"]["first_epoch"]
        # prune-health drops old samples
        assert ins.prune_health(1100.0) == 2
        assert ins.report(now=1050.0)["health"]["samples"] == 0
        # crashes ride the report, windowed by their stamp
        c.crash_osd(1)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if r.mon_command({"prefix": "crash ls"})[2]:
                break
            time.sleep(0.05)
        mgr.observability_tick()
        now = time.time()
        rep = ins.report(now=now)
        assert [cr["entity_name"] for cr in rep["crashes"]] == ["osd.1"]
        assert ins.report(now=now + 1000.0)["crashes"] == []
    finally:
        c.shutdown()


# ----------------------------------------------------------------- CLI

def test_vstart_observability_verbs():
    """The vstart shell tour of the new subsystem: crash-osd ->
    crash ls -> health warns -> archive clears -> telemetry/insights
    render."""
    from ceph_tpu.tools.vstart import VstartShell
    out = iomod.StringIO()
    sh = VstartShell(n_osd=3, osds_per_host=1, out=out)
    try:
        for line in ["crash ls", "crash-osd 2", "crash ls", "health",
                     "crash archive-all", "health", "telemetry show",
                     "insights", "crash prune 0", "crash ls"]:
            assert sh.run_line(line)
        text = out.getvalue()
        assert "osd.2 crashed" in text
        assert '"entity_name": "osd.2"' in text
        assert "RECENT_CRASH" in text                 # pre-archive
        assert "HEALTH_OK" in text                    # post-archive
        assert '"cluster_id"' in text                 # telemetry
        assert '"window_seconds"' in text             # insights
    finally:
        sh.close()


def test_observability_cli_verbs():
    c = MiniCluster(n_osd=3, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        mgr = c.start_mgr()
        mgr.start_crash()
        mgr.start_telemetry()
        mgr.start_insights()
        c.crash_osd(1)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if r.mon_command({"prefix": "crash ls"})[2]:
                break
            time.sleep(0.05)
        mgr.observability_tick()
        from ceph_tpu.tools.rados_cli import main

        def run(*argv):
            out = iomod.StringIO()
            rc = main(list(argv), rados=r, out=out)
            return rc, out.getvalue()

        rc, out = run("crash", "ls")
        assert rc == 0 and "osd.1" in out
        cid = json.loads(out)[0]["crash_id"]
        rc, out = run("crash", "info", cid)
        assert rc == 0 and "backtrace" in out
        assert run("crash", "info")[0] == 1          # id required
        rc, out = run("telemetry", "status")
        assert rc == 0 and '"enabled": true' in out
        rc, out = run("telemetry")                    # default: show
        assert rc == 0 and json.loads(out)["cluster_id"]
        rc, out = run("insights")
        assert rc == 0 and "window_seconds" in out
        rc, out = run("crash", "archive", cid)
        assert rc == 0
        rc, out = run("crash", "ls-new")
        assert rc == 0 and json.loads(out) == []
        rc, out = run("crash", "prune", "0")
        assert rc == 0
        rc, out = run("crash", "ls")
        assert json.loads(out) == []
    finally:
        c.shutdown()


def test_telemetry_upload_target(tmp_path):
    """The mgr_telemetry_url sink (the dashboard-item's second half):
    each observability tick posts the compiled report to a file:// or
    http:// target, `telemetry status` carries the last-send outcome,
    and an unreachable sink records a failure instead of killing the
    tick."""
    import urllib.request
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    from ceph_tpu.common.options import global_config

    cfg = global_config()
    old_url = cfg["mgr_telemetry_url"]
    c = MiniCluster(n_osd=3, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        mgr = c.start_mgr()
        tm = mgr.start_telemetry()
        # --- file:// sink: one JSON line per send ---
        sink = tmp_path / "telemetry.jsonl"
        cfg.set("mgr_telemetry_url", f"file://{sink}")
        mgr.observability_tick()
        mgr.observability_tick()
        lines = sink.read_text().strip().splitlines()
        assert len(lines) == 2
        rep = json.loads(lines[-1])
        assert rep["cluster_id"] == tm.cluster_id()
        rc, _, st = r.mon_command({"prefix": "telemetry status"})
        assert rc == 0 and st["last_send"]["ok"] is True
        assert st["last_send"]["url"].startswith("file://")
        assert st["url"].startswith("file://")
        # --- http:// sink: POSTed body is the report ---
        got = []

        class Sink(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                got.append(json.loads(self.rfile.read(n)))
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), Sink)
        import threading
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        try:
            cfg.set("mgr_telemetry_url",
                    f"http://127.0.0.1:{httpd.server_address[1]}/")
            mgr.observability_tick()
            assert got and got[0]["cluster_id"] == tm.cluster_id()
            # forced resend via the CLI verb
            rc, outs, outb = r.mon_command(
                {"prefix": "telemetry send"})
            assert rc == 0 and len(got) == 2
        finally:
            httpd.shutdown()
            httpd.server_close()
        # --- unreachable sink: failure recorded, tick survives ---
        cfg.set("mgr_telemetry_url",
                f"http://127.0.0.1:{httpd.server_address[1]}/")
        mgr.observability_tick()
        rc, _, st = r.mon_command({"prefix": "telemetry status"})
        assert rc == 0 and st["last_send"]["ok"] is False
        assert st["last_send"]["error"]
        # --- no sink configured: nothing recorded anew ---
        cfg.set("mgr_telemetry_url", "")
        tm.last_send = None
        mgr.observability_tick()
        rc, _, st = r.mon_command({"prefix": "telemetry status"})
        assert rc == 0 and st["last_send"] is None \
            and st["url"] is None
    finally:
        cfg.set("mgr_telemetry_url", old_url)
        c.shutdown()
