"""cls_rgw: bucket-index transactions executed inside the OSD.

The reference maintains its bucket index with cls methods running on
the index object's primary OSD (ref: src/cls/rgw/cls_rgw.cc,
cls_rgw_ops.h), so every gateway's read-modify-write of an index entry
serializes on the PG — not on any gateway-local lock.  Same contract
here: each method below reads the current entry, computes the new
version stack, and queues the omap update; the daemon runs the method
under its dispatch lock and commits the mutation atomically with the
reply (osd/daemon.py _do_exec).  Two radosgw processes over one pool
therefore cannot lose a concurrent PUT's version record.

Entry format (JSON, one omap value per key; shared with
rgw/gateway.py):
  plain:     {"size", "etag", "mtime"}
  versioned: {"versions": [head..tail], "size", "etag", "mtime", "dm"}
Each version: {"vid", "size", "etag", "mtime", "dm", "obj"} where
"obj" names the RADOS data object backing that version (None for
delete markers).

Methods return the data objects orphaned by the operation in
"removed" — the gateway deletes those AFTER the index commit, the
same order the reference uses (index transaction first, data gc
second) so a crash leaves garbage, never a dangling index entry.
"""
from __future__ import annotations

import json
import time

from . import CLS_METHOD_WR, ClsError, cls_method

#: the one timestamp format for index entries — shared with the
#: gateway (rgw/gateway.py imports these; a format drift between
#: writer and OSD-side trimmer would misage every version)
MTIME_FMT = "%Y-%m-%dT%H:%M:%S.000Z"


def now_str() -> str:
    return time.strftime(MTIME_FMT, time.gmtime())


def parse_mtime(s: str) -> float:
    try:
        return time.mktime(time.strptime(s, MTIME_FMT)) - time.timezone
    except ValueError:
        return 0.0


def _load(ctx, key: str) -> dict | None:
    raw = ctx.omap_get().get(key)
    return json.loads(raw) if raw else None


def _fold(ent: dict | None, plain_obj: str | None) -> list:
    """Existing version stack; a pre-versioning plain entry becomes
    the S3 'null' version backed by the plain data object
    (ref: rgw null-version semantics)."""
    if ent is None:
        return []
    if ent.get("versions") is not None:
        return ent["versions"]
    return [{"vid": "null", "size": ent["size"], "etag": ent["etag"],
             "mtime": ent["mtime"], "dm": False,
             "obj": ent.get("obj") or plain_obj}]


def _store(ctx, key: str, versions: list) -> None:
    if not versions:
        ctx.omap_rmkeys([key])
        return
    head = versions[0]
    meta = {"versions": versions, "size": head.get("size", 0),
            "etag": head.get("etag", ""), "mtime": head["mtime"],
            "dm": bool(head.get("dm"))}
    ctx.omap_set({key: json.dumps(meta).encode()})


@cls_method("rgw", "obj_store", CLS_METHOD_WR)
def obj_store(ctx, d):
    """Record a completed PUT in the index
    (ref: cls_rgw bucket_complete_op CLS_RGW_OP_ADD).

    mode "plain": unversioned entry, last writer wins per key.
    mode "enabled": push a new version onto the stack.
    mode "suspended": replace the 'null' version in place.

    Every mode writes its data to a FRESH object first and links it
    here (the reference's instance-object model); the entry this
    commit orphans comes back in "removed" so the caller can gc it —
    a plain overwrite therefore never clobbers bytes a concurrent
    reader (or a version stack that appeared meanwhile) still needs.
    """
    key, mode = d["key"], d.get("mode", "plain")
    ent = _load(ctx, key)
    if mode == "plain":
        if ent is not None and ent.get("versions") is not None:
            # versioning got enabled (and a version committed) after
            # the caller read the bucket meta — a plain overwrite
            # would erase that stack.  Caller retries as versioned.
            raise ClsError("ECANCELED", key)
        removed = []
        old = (ent.get("obj") or d.get("plain_obj")) \
            if ent is not None else None
        if old and old != d["obj"]:
            removed.append(old)
        ctx.omap_set({key: json.dumps(
            {"size": d["size"], "etag": d["etag"],
             "mtime": d["mtime"], "obj": d["obj"]}).encode()})
        return {"vid": None, "removed": removed}
    versions = _fold(ent, d.get("plain_obj"))
    rec = {"vid": d["vid"], "size": d["size"], "etag": d["etag"],
           "mtime": d["mtime"], "dm": False, "obj": d["obj"]}
    removed = []
    if mode == "suspended":
        for v in versions:
            if v["vid"] == "null" and not v.get("dm") and v.get("obj") \
                    and v["obj"] != d["obj"]:
                removed.append(v["obj"])
        versions = [v for v in versions if v["vid"] != "null"]
        rec["vid"] = "null"
    elif mode != "enabled":
        raise ClsError("EINVAL", f"mode {mode}")
    versions.insert(0, rec)
    _store(ctx, key, versions)
    return {"vid": rec["vid"], "removed": removed}


@cls_method("rgw", "obj_delete_marker", CLS_METHOD_WR)
def obj_delete_marker(ctx, d):
    """Insert a delete marker at the head of the stack (ref: rgw
    delete-marker flow, cls_rgw CLS_RGW_OP_LINK_OLH_DM).

    replace_null: drop the existing 'null' version first (Suspended
    buckets replace the null version with a null marker); its data
    object comes back in "removed".
    if_head_vid / if_mtime: optional guards — ECANCELED when the head
    changed since the caller's read (lifecycle uses them so an expiry
    decided on a stale snapshot never clobbers a fresh PUT).  BOTH are
    needed: a Suspended-bucket overwrite keeps vid "null", so only the
    mtime moves.
    """
    key = d["key"]
    versions = _fold(_load(ctx, key), d.get("plain_obj"))
    if "if_head_vid" in d:
        head = versions[0]["vid"] if versions else None
        if head != d["if_head_vid"]:
            raise ClsError("ECANCELED", key)
    if "if_mtime" in d:
        head_mtime = versions[0]["mtime"] if versions else None
        if head_mtime != d["if_mtime"]:
            raise ClsError("ECANCELED", key)
    removed = []
    if d.get("replace_null"):
        for v in versions:
            if v["vid"] == "null" and not v.get("dm") and v.get("obj"):
                removed.append(v["obj"])
        versions = [v for v in versions if v["vid"] != "null"]
    versions.insert(0, {"vid": d["vid"], "size": 0, "etag": "",
                        "mtime": d["mtime"], "dm": True, "obj": None})
    _store(ctx, key, versions)
    return {"vid": d["vid"], "removed": removed}


@cls_method("rgw", "obj_delete_version", CLS_METHOD_WR)
def obj_delete_version(ctx, d):
    """Remove one explicit version (ref: cls_rgw
    CLS_RGW_OP_UNLINK_INSTANCE).  ENOENT when the vid isn't in the
    stack; an emptied stack removes the index entry."""
    key = d["key"]
    ent = _load(ctx, key)
    if ent is None:
        raise ClsError("ENOENT", key)
    versions = _fold(ent, d.get("plain_obj"))
    keep = [v for v in versions if v["vid"] != d["vid"]]
    if len(keep) == len(versions):
        raise ClsError("ENOENT", d["vid"])
    removed = [v["obj"] for v in versions
               if v["vid"] == d["vid"] and v.get("obj")
               and not v.get("dm")]
    _store(ctx, key, keep)
    return {"removed": removed}


@cls_method("rgw", "obj_delete_plain", CLS_METHOD_WR)
def obj_delete_plain(ctx, d):
    """Unversioned delete: drop the index entry (ref: cls_rgw
    CLS_RGW_OP_DEL).  ECANCELED if the entry meanwhile grew a version
    stack — the caller re-runs the versioned delete path.
    if_mtime: optional guard for lifecycle (see obj_delete_marker)."""
    key = d["key"]
    ent = _load(ctx, key)
    if ent is None:
        return {"removed": []}
    if ent.get("versions") is not None:
        raise ClsError("ECANCELED", key)
    if "if_mtime" in d and ent.get("mtime") != d["if_mtime"]:
        raise ClsError("ECANCELED", key)
    ctx.omap_rmkeys([key])
    dead = ent.get("obj") or d.get("plain_obj")
    return {"removed": [dead] if dead else []}


@cls_method("rgw", "obj_trim_noncurrent", CLS_METHOD_WR)
def obj_trim_noncurrent(ctx, d):
    """Drop noncurrent versions older than max_age_s (lifecycle
    NoncurrentVersionExpiration; ref: src/rgw/rgw_lc.cc noncurrent
    expiry).  The age test runs HERE against the committed stack, so
    two gateways' lifecycle ticks can race without double-freeing."""
    key = d["key"]
    ent = _load(ctx, key)
    if ent is None or ent.get("versions") is None:
        return {"removed": [], "dropped": 0}
    versions = ent["versions"]
    keep, removed = versions[:1], []
    for v in versions[1:]:
        if d["now"] - parse_mtime(v["mtime"]) > d["max_age_s"]:
            if v.get("obj") and not v.get("dm"):
                removed.append(v["obj"])
        else:
            keep.append(v)
    if len(keep) != len(versions):
        _store(ctx, key, keep)
    return {"removed": removed, "dropped": len(versions) - len(keep)}
