"""CLI entry: ``python -m ceph_tpu.analysis [paths...]``."""
import sys

from .engine import main

if __name__ == "__main__":
    sys.exit(main())
