"""Throughput probe on the real TPU: XLA vs Pallas GF matmul paths."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp

from ceph_tpu.ec import gf
from ceph_tpu.ec.kernels import bitmatmul

k, m = 8, 4
chunk = 128 * 1024          # 1 MiB object / k=8
stripes = 32                # batch per dispatch
rng = np.random.default_rng(0)
mat = gf.isa_rs_matrix(k, m)[k:]
data_np = rng.integers(0, 256, (stripes, k, chunk), dtype=np.uint8)
data = jnp.asarray(data_np)
B = jnp.asarray(gf.expand_to_bitmatrix(mat).astype(np.int8))


def bench(fn, label, iters=20):
    out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    total = stripes * k * chunk
    print(f"{label}: {dt*1e3:.2f} ms  {total/dt/1e9:.2f} GB/s (data in)")
    return out


xla = bench(lambda: bitmatmul.gf_matmul_xla(B, data), "xla   ")
flat = data.reshape(1, k, -1)  # treat batch as one wide N? no: per-stripe axes
pallas = bench(lambda: bitmatmul.gf_matmul_pallas(B, data), "pallas")
got = np.asarray(pallas)
want = np.asarray(xla)
print("parity:", np.array_equal(got, want))
want0 = gf.gf_matmul_bytes(mat, data_np[0])
print("oracle:", np.array_equal(got[0], want0))
