"""Compressor registry + RadosStriper API
(ref: src/compressor/Compressor.cc, src/libradosstriper/)."""
import numpy as np
import pytest

from ceph_tpu.compressor import compress, decompress, registry
from ceph_tpu.osdc.rados_striper import RadosStriper
from ceph_tpu.osdc.striper import StripeLayout
from ceph_tpu.testing import MiniCluster


def test_compressor_roundtrip_all():
    data = b"the quick brown fox " * 500
    for alg in registry.supported():
        blob = compress(data, alg)
        assert decompress(blob) == data, alg
    with pytest.raises(ValueError):
        registry.create("snappy-nope")


def test_compressor_stored_raw_fallback():
    rnd = np.random.default_rng(1).integers(
        0, 256, 4096, dtype=np.uint8).tobytes()
    blob = compress(rnd, "zlib")
    # incompressible input stays raw (alg tag 'none')
    assert b"none" in blob[:16]
    assert decompress(blob) == rnd
    assert len(blob) < len(rnd) + 32


def test_rados_striper(request):
    c = MiniCluster(n_osd=4, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        r.pool_create("stp", pg_num=8)
        io = r.open_ioctx("stp")
        st = RadosStriper(io, StripeLayout(stripe_unit=1 << 12,
                                           stripe_count=3,
                                           object_size=1 << 14))
        payload = np.random.default_rng(3).integers(
            0, 256, 150_000, dtype=np.uint8).tobytes()
        st.write("big", payload)
        assert st.read("big") == payload
        assert st.read("big", length=100, offset=70_000) == \
            payload[70_000:70_100]
        meta = st.stat("big")
        assert meta["size"] == len(payload)
        assert meta["stripe_count"] == 3
        # the data really is spread over many rados objects
        objs = [o for o in io.list_objects() if o.startswith("big.")]
        assert len(objs) > 5
        # offset write extends
        st.write("big", b"TAIL", offset=len(payload))
        assert st.read("big")[-4:] == b"TAIL"
        st.remove("big")
        assert not [o for o in io.list_objects()
                    if o.startswith("big.")]
    finally:
        c.shutdown()
