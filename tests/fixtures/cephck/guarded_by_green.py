"""GREEN: every access takes the inferred guard — including a
private helper that touches the table bare but is ONLY reached from
locked callers (the call-graph coverage path)."""
from ceph_tpu.common.lockdep import make_lock


class PGMetaTable:
    def __init__(self):
        self._lock = make_lock("fixture.pgmeta")
        self._table = {}

    def put(self, k, v):
        with self._lock:
            self._table[k] = v
            self._compact()

    def get(self, k):
        with self._lock:
            return self._table.get(k)

    def merge(self, other):
        with self._lock:
            self._table.update(other)
            self._compact()
            return len(self._table)

    def snapshot(self):
        with self._lock:
            return dict(self._table)

    def _compact(self):
        # bare access, but every caller holds self._lock: covered
        # through the project call graph, not flagged
        if len(self._table) > 64:
            self._table.pop(next(iter(self._table)))
