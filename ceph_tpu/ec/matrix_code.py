"""Shared machinery for matrix-based (MDS) erasure codes.

Models what ISA-L/jerasure matrix codes do around the GF matmul
(ref: src/erasure-code/isa/ErasureCodeIsa.cc isa_encode/isa_decode,
src/erasure-code/jerasure/ErasureCodeJerasure.cc jerasure_encode/decode):

* encode: coding chunks = (m x k coding submatrix) x (k data chunks);
* decode: pick the first k surviving chunks in index order
  ("decode_index", ref: ErasureCodeIsa.cc:231-247), invert the k x k
  survivor submatrix, build decode rows for erased data chunks directly
  from the inverse and for erased coding chunks by re-projecting through
  the encode matrix (ref: ErasureCodeIsa.cc:281-294), then one matmul;
* decode tables are cached per erasure signature, mirroring the ISA-L
  table cache (ref: src/erasure-code/isa/ErasureCodeIsaTableCache.cc).

The byte matmul itself is pluggable (`matmul`), so the same orchestration
drives the numpy CPU oracle and the TPU (JAX/Pallas) kernels.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Iterable, Mapping

import numpy as np

from . import gf
from ..common.racecheck import shared_state
from .interface import ErasureCode, ErasureCodeError

MatmulFn = Callable[[np.ndarray, np.ndarray], np.ndarray]


# one plugin instance serves every PG of a profile, so concurrent
# decodes hit the LRU from many threads: the racecheck sanitizer
# checks that every access really goes through self._lock (`_lru` is
# mutating — an LRU get() reorders the dict, so reads count as writes)
@shared_state(only=("_lru", "_cost"), mutating=("_lru", "_cost"))
class DecodeTableCache:
    """Cost-weighted LRU of decode tables keyed by erasure signature
    (ref: ErasureCodeIsaTableCache.cc, decoding_tables_lru_length).

    `cost` weights an entry against the capacity: a full-width
    (nerrs x n) matrix — or the HBM-resident kernel object built from
    one — is ~(k+m)/k x the footprint of the dense (nerrs x k) table,
    so full-matrix signatures charge more and the bound stays a real
    memory bound, not an entry count.  Values are opaque (ndarray or
    compiled-kernel wrappers alike)."""

    def __init__(self, capacity: int = 2516):
        from ..common.lockdep import make_lock
        self.capacity = capacity
        self._lru: OrderedDict[str, tuple[object, int]] = OrderedDict()
        self._cost = 0
        # the daemon shares ONE plugin instance per profile across all
        # its PGs, so concurrent decodes hit this cache from multiple
        # threads — and unlike the plain dict this replaced, an LRU
        # mutates on every GET (move_to_end)
        self._lock = make_lock("ec.decode_table_cache")

    def __len__(self) -> int:
        with self._lock:
            return len(self._lru)

    def total_cost(self) -> int:
        with self._lock:
            return self._cost

    def get(self, sig: str):
        with self._lock:
            entry = self._lru.get(sig)
            if entry is None:
                return None
            self._lru.move_to_end(sig)
            return entry[0]

    def put(self, sig: str, mat, cost: int = 1) -> None:
        with self._lock:
            old = self._lru.pop(sig, None)
            if old is not None:
                self._cost -= old[1]
            self._lru[sig] = (mat, cost)
            self._cost += cost
            while self._cost > self.capacity and len(self._lru) > 1:
                _, (_, c) = self._lru.popitem(last=False)
                self._cost -= c


def erasure_signature(decode_index: list[int], erasures: list[int]) -> str:
    """"+r..-e.." signature string (ref: ErasureCodeIsa.cc:231-247)."""
    return "".join(f"+{r}" for r in decode_index) + \
           "".join(f"-{e}" for e in erasures)


def make_decode_matrix(encode_matrix: np.ndarray, k: int,
                       decode_index: list[int], erasures: list[int]
                       ) -> np.ndarray:
    """(nerrs x k) decode matrix applied to the k survivor chunks.

    encode_matrix is the full (k+m) x k matrix (identity top).  Mirrors the
    ISA-L construction: invert the survivor submatrix b; for an erased data
    chunk e the decode row is inv_b[e]; for an erased coding chunk c the row
    is encode_row(c) @ inv_b (ref: ErasureCodeIsa.cc:252-294).
    """
    b = encode_matrix[decode_index, :]  # (k x k) survivor rows
    inv_b = gf.gf_invert_matrix(b)
    if inv_b is None:
        raise ErasureCodeError("EIO: singular survivor matrix")
    rows = []
    for e in erasures:
        if e < k:
            rows.append(inv_b[e])
        else:
            rows.append(gf.gf_matmul(encode_matrix[e][None, :], inv_b)[0])
    return np.stack(rows).astype(np.uint8)


def make_decode_matrix_full(encode_matrix: np.ndarray, k: int, n: int,
                            decode_index: list[int],
                            erasures: list[int]) -> np.ndarray:
    """(nerrs x n) decode matrix over ALL n=k+m chunk slots.

    Columns outside `decode_index` are zero, so the matmul consumes the
    full chunk array in place — erased/unused slots contribute nothing
    regardless of content and the survivor gather disappears entirely
    (device-resident survivor selection: the selection IS the matrix).
    The ISA-L analogue keeps gathering into dense buffers
    (ErasureCodeIsa.cc:252-306); on the MXU the zero columns ride for
    free in the same tiles."""
    dmat = make_decode_matrix(encode_matrix, k, decode_index, erasures)
    full = np.zeros((len(erasures), n), dtype=np.uint8)
    full[:, decode_index] = dmat
    return full


class MatrixErasureCode(ErasureCode):
    """Systematic MDS matrix code with pluggable matmul.

    Default field is GF(2^8) (the byte fast path in ceph_tpu.ec.gf);
    setting `self.field` to a ceph_tpu.ec.gfw.GF2w switches the matmul
    and decode-matrix construction to that wide-word field (jerasure's
    w=16/32 matrix techniques)."""

    def __init__(self) -> None:
        super().__init__()
        self.k = 0
        self.m = 0
        self.encode_matrix: np.ndarray | None = None  # (k+m) x k, identity top
        self.field = None                             # None = GF(2^8)
        self.table_cache = DecodeTableCache()

    # subclasses set self.k/self.m and call _prepare with the full matrix
    def _prepare(self, encode_matrix: np.ndarray) -> None:
        assert encode_matrix.shape == (self.k + self.m, self.k)
        dtype = np.uint8 if self.field is None else np.int64
        self.encode_matrix = np.ascontiguousarray(encode_matrix,
                                                  dtype=dtype)

    # the matmul backend; TPU plugin overrides
    def matmul(self, mat: np.ndarray, data: np.ndarray) -> np.ndarray:
        if self.field is not None:
            return self.field.matmul_bytes(mat, data)
        return gf.gf_matmul_bytes(mat, data)

    def _make_decode_matrix(self, decode_index: list[int],
                            erasures: list[int]) -> np.ndarray:
        if self.field is None:
            return make_decode_matrix(self.encode_matrix, self.k,
                                      decode_index, erasures)
        f = self.field
        b = [list(self.encode_matrix[i]) for i in decode_index]
        inv_b = f.invert_matrix(b)
        if inv_b is None:
            raise ErasureCodeError("EIO: singular survivor matrix")
        rows = []
        for e in erasures:
            if e < self.k:
                rows.append(inv_b[e])
            else:
                rows.append(f.matmul_small(
                    [list(self.encode_matrix[e])], inv_b)[0])
        return np.array(rows, dtype=np.int64)

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def repair_schedule(self, erasures: set, available: set):
        """MDS fallback plan: k full survivor chunks (the same
        first-k-in-index-order selection as decode_chunks, so the
        compiled matrix IS the cached decode matrix) rebuilding every
        lost shard directly — no decode-to-logical + re-encode round
        trip.  Wide-word fields (gfw w=16/32) are not byte-linear, so
        they stay on the interpreted path."""
        if self.field is not None:
            return None
        erasures = set(erasures)
        avail = sorted(set(available) - erasures)
        if not erasures or len(erasures) > self.m or len(avail) < self.k:
            return None
        from .repairc import RepairPlan
        return RepairPlan.make(
            erasures, {h: [(0, 1)] for h in avail[:self.k]},
            sub_chunk_no=1)

    # -- math --------------------------------------------------------------
    def encode_chunks(self, want_to_encode: Iterable[int],
                      encoded: dict[int, np.ndarray]) -> None:
        k, m = self.k, self.m
        data = np.stack([encoded[self.chunk_index(i)] for i in range(k)])
        coding = self.matmul(self.encode_matrix[k:], data)
        for i in range(m):
            encoded[self.chunk_index(k + i)][...] = coding[i]

    def decode_chunks(self, want_to_read: Iterable[int],
                      chunks: Mapping[int, np.ndarray],
                      decoded: dict[int, np.ndarray]) -> None:
        k, m = self.k, self.m
        avail = set(chunks)
        erasures = [i for i in range(k + m) if i not in avail]
        if len(erasures) > m:
            raise ErasureCodeError("EIO: too many erasures")
        # first k surviving chunks in index order (ErasureCodeIsa.cc:231)
        decode_index = [i for i in range(k + m) if i in avail][:k]
        if len(decode_index) < k:
            raise ErasureCodeError("EIO: fewer than k chunks available")
        sig = erasure_signature(decode_index, erasures)
        dmat = self.table_cache.get(sig)
        if dmat is None:
            dmat = self._make_decode_matrix(decode_index, erasures)
            self.table_cache.put(sig, dmat)
        survivors = np.stack([decoded[i] for i in decode_index])
        out = self.matmul(dmat, survivors)
        for row, e in enumerate(erasures):
            decoded[e][...] = out[row]
