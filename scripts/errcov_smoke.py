#!/usr/bin/env python
"""errcov smoke — the error-path coverage half of the ship gate
(check_green.sh).

Boots a MiniCluster with errcheck armed and drives a deliberately
faulted mini workload — missing-object reads, cls EINVAL/EOPNOTSUPP
calls, EC shard reads failing with injected EIO
(objectstore_debug_inject_read_err), and a FaultPlane message-drop
window — so real error handlers FIRE, then:

1. asserts the known handlers did fire (an EC-read error path in
   osd/ec_backend and a cls-call error path — if those stay cold the
   sanitizer is a no-op and the gate is blind);
2. writes ERRCOV_r01.json: per-module fired/total handler ratios and
   the never-fired list from errcheck.coverage_report();
3. ratchets: the never-fired count must not grow past the committed
   ERRCOV_r01.json (+ a small jitter allowance for timing-dependent
   handlers) — error paths may only GAIN coverage.

Run from the repo root: python scripts/errcov_smoke.py
"""
import json
import os
import pathlib
import sys
import tempfile

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

# arm BEFORE any other ceph_tpu import: the hook only instruments
# modules imported after it installs
os.environ["CEPH_TPU_ERRCHECK"] = "1"
_DUMPDIR = tempfile.mkdtemp(prefix="errcov-")
os.environ["CEPH_TPU_ERRCHECK_DIR"] = _DUMPDIR

from ceph_tpu.common import errcheck            # noqa: E402

if not errcheck.enable_if_configured():
    print("errcov smoke: sanitizer did not arm", file=sys.stderr)
    sys.exit(1)

from ceph_tpu.client import RadosError          # noqa: E402
from ceph_tpu.common.options import global_config  # noqa: E402
from ceph_tpu.testing import MiniCluster        # noqa: E402

ARTIFACT = ROOT / "ERRCOV_r01.json"
#: run-to-run jitter allowance on the ratchet: a handful of handlers
#: are timing-dependent (heartbeat grace, backoff windows) and may or
#: may not fire within one short smoke — the ratchet tolerates that
#: noise while still failing a real coverage regression
RATCHET_SLACK = 5
K, M = 2, 1


def expect(exc_match, fn, *args, **kw):
    """Run fn expecting a RadosError containing exc_match."""
    try:
        fn(*args, **kw)
    except RadosError as ex:
        assert exc_match in str(ex), (exc_match, ex)
        return
    raise AssertionError(f"{fn} did not raise {exc_match}")


def drive_workload() -> None:
    # fast heartbeats so the FaultPlane drop window below sees real
    # traffic within the smoke's budget (daemons read this at init)
    global_config().set("osd_heartbeat_interval", 0.25)
    c = MiniCluster(n_osd=4, threaded=True, fault_seed=7)
    try:
        c.wait_all_up()
        r = c.rados()
        r.pool_create("meta", pg_num=8)
        r.mon_command({"prefix": "osd erasure-code-profile set",
                       "name": "k2m1",
                       "profile": {"plugin": "tpu", "k": str(K),
                                   "m": str(M),
                                   "crush-failure-domain": "osd"}})
        r.pool_create("ecp", pg_num=8, pool_type="erasure",
                      erasure_code_profile="k2m1")
        io = r.open_ioctx("meta")

        # -- client/cls error paths ---------------------------------
        expect("ENOENT", io.read, "never-written")
        expect("ENOENT", io.stat, "never-written")
        expect("EOPNOTSUPP", io.exec, "o", "no-such-class", "x")
        io.exec("ctr", "numops", "add", {"key": "n", "value": 2})
        expect("EINVAL", io.exec, "ctr", "numops", "add",
               {"key": "n", "value": "three"})
        expect("EINVAL", io.exec, "ctr", "numops", "div",
               {"key": "n", "value": 0})

        # -- EC shard EIO: reconstructing-read error path -----------
        cfg = global_config()
        cfg.set("objectstore_debug_inject_read_err", True)
        try:
            ec = r.open_ioctx("ecp")
            payload = bytes((i * 37) % 256 for i in range(1 << 14))
            ec.write_full("eobj", payload)
            pid = r.pool_lookup("ecp")
            m = c.mon.osdmap
            raw = m.object_locator_to_pg("eobj", pid)
            pg = m.pools[pid].raw_pg_to_pg(raw)
            _, _, acting, primary = m.pg_to_up_acting_osds(raw)
            victim_shard = next(s for s in range(K)
                                if acting[s] != primary
                                and acting[s] >= 0)
            st = c.osds[acting[victim_shard]].pgs[pg]
            st.shard.inject_read_err("eobj")
            assert ec.read("eobj") == payload   # reconstructs anyway
            st.shard.clear_read_err("eobj")
        finally:
            cfg.set("objectstore_debug_inject_read_err", False)

        # -- rbd/journal error paths --------------------------------
        from ceph_tpu.journal import Journaler, data_obj
        from ceph_tpu.rbd import RBD
        from ceph_tpu.rbd.image import RBDError, header_name
        from ceph_tpu.rbd.mirror import _load_meta
        RBD().create(io, "vm", size=1 << 18, order=16, journaling=True)
        io.write_full(header_name("vm"), b"\xffnot json")
        try:
            _load_meta(io, "vm")        # corrupt header -> EIO
        except RBDError as ex:
            assert ex.errno == 5
        try:
            _load_meta(io, "gone")      # missing image -> ENOENT
        except RBDError as ex:
            assert ex.errno == 2
        j = Journaler(io, "torn", "master")
        j.create()
        j.register_client()
        j.append("ok", {"v": 1})
        io.append(data_obj("torn", 0), b"\x00\x01\x02torn!")
        got = []
        j.replay(lambda t, d: got.append(d["v"]))   # torn-tail handler
        assert got == [1]

        # -- serve: lost-object sparse reads + CLI error paths ------
        from io import StringIO
        from ceph_tpu.serve import ArtifactStore
        from ceph_tpu.tools import rados_cli
        st = ArtifactStore(io, page_size=4096)
        # first put probes for a prior manifest: ENOENT -> epoch 1
        m1 = st.put("smoke-art", shards={"s": b"\x5a" * (3 * 4096 + 7)})
        assert m1.epoch == 1
        io.remove(sorted(m1.data_oids())[-1])   # lose a data object
        # sparse semantics: BOTH fetch paths read the hole as zeros
        # (the batched wave and the per-page loop hit distinct
        # ENOENT-tolerant handlers)
        wave = st.fetch_pages("smoke-art", "s", [0, 1, 2, 3])
        loop = st.fetch_pages("smoke-art", "s", [0, 1, 2, 3],
                              batched=False)
        assert wave == loop
        # epoch flip over the half-removed epoch: cleanup tolerates
        # already-gone objects
        assert st.put("smoke-art", shards={"s": b"\xa5" * 4096}
                      ).epoch == 2
        # CLI: malformed page-id list reports usage, not a traceback
        assert rados_cli.main(["serve", "pages", "meta", "smoke-art",
                               "s", "0,zap"], rados=r,
                              out=StringIO()) == 1

        # -- mon command error paths --------------------------------
        try:
            r.mon_command({"prefix": "no such command"})
        except RadosError:
            pass
        try:
            r.pool_create("meta", pg_num=8)     # EEXIST
        except RadosError:
            pass

        # -- FaultPlane: a lossy heartbeat window (heartbeats fire on
        # harness ticks, so drive them explicitly under the rule) ----
        plane = c.network.faults
        rid = plane.add_rule("osd.*", "osd.*", drop=0.3,
                             types=["Ping"])
        for _ in range(12):
            c.tick()
        plane.remove_rule(rid)
        plane.flush()
        for _ in range(4):
            c.tick()            # heal: peers re-ping cleanly
        assert plane.counts.get("drop", 0) > 0, \
            "fault plane never bit"

        # -- OSD flap: down/up peering churn under live data --------
        c.kill_osd(3)
        for _ in range(6):
            c.tick()
        c.revive_osd(3)
        for _ in range(6):
            c.tick()
        # data written before the flap still reads back
        assert io.exec("ctr", "numops", "add",
                       {"key": "n", "value": 1})["value"] == 3
    finally:
        c.shutdown()


def main() -> int:
    drive_workload()

    fired = errcheck.merge_dir(_DUMPDIR)
    fired_modules = {m for (m, _ln, _exc) in fired}

    # the sanitizer must have seen the error paths the workload forced
    for want in ("ceph_tpu.osd.ec_backend", "ceph_tpu.cls"):
        if not any(m == want or m.startswith(want + ".")
                   for m in fired_modules):
            print(f"errcov smoke: FAIL — no handler fired under "
                  f"{want}; the coverage hook is blind", file=sys.stderr)
            return 1

    rep = errcheck.coverage_report(str(ROOT / "ceph_tpu"),
                                   package="ceph_tpu", fired=fired)
    new_never = rep["never_fired_count"]

    if ARTIFACT.exists():
        old = json.loads(ARTIFACT.read_text())
        old_never = old.get("never_fired_count")
        if old_never is not None and \
                new_never > old_never + RATCHET_SLACK:
            print(f"errcov smoke: FAIL — never-fired handlers grew "
                  f"{old_never} -> {new_never} (slack "
                  f"{RATCHET_SLACK}); error paths lost coverage.\n"
                  f"If handlers were legitimately added, exercise "
                  f"them here or in tier-1, then regenerate "
                  f"ERRCOV_r01.json with this script.",
                  file=sys.stderr)
            return 1

    ARTIFACT.write_text(json.dumps(rep, indent=1) + "\n")
    print(f"errcov smoke: OK — {rep['handlers_fired']}/"
          f"{rep['handlers_total']} handlers fired "
          f"(ratio {rep['ratio']}), {new_never} never fired "
          f"({ARTIFACT.name} updated)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
