"""Secure wire mode: sealed TCP frames (ref: msgr v2 SECURE mode,
src/msg/async/crypto_onwire.cc — closing VERDICT r2 missing #9)."""
import socket
import struct
import threading

import pytest

from ceph_tpu.msg.secure import SecureSession
from ceph_tpu.msg.tcp import (TcpNet, pick_free_ports, recv_frame,
                              send_frame)
from ceph_tpu.msg.messenger import Dispatcher, Messenger


def test_session_roundtrip_and_tamper():
    s = SecureSession("s3cret", "frame")
    for size in (0, 1, 100, 5000, 1 << 16):
        pt = bytes(range(256)) * (size // 256) + b"x" * (size % 256)
        blob = s.seal(pt)
        assert blob != pt
        assert s.open(blob) == pt
        # every bit flip must fail authentication
        bad = bytearray(blob)
        bad[len(bad) // 2] ^= 1
        assert s.open(bytes(bad)) is None
    # wrong key never opens
    other = SecureSession("wrong", "frame")
    assert other.open(s.seal(b"secret data")) is None
    # nonces differ: same plaintext -> different ciphertext
    assert s.seal(b"same") != s.seal(b"same")


def test_no_plaintext_on_the_wire():
    """Sniff the raw socket bytes between two secure endpoints: the
    payload marker must never appear in the clear."""
    from ceph_tpu.msg.messages import OSDOp
    ports = pick_free_ports(2)
    addrs = {"osd.0": ("127.0.0.1", ports[0]),
             "osd.1": ("127.0.0.1", ports[1])}
    marker = b"TOP-SECRET-PAYLOAD-MARKER"
    captured = {}
    done = threading.Event()

    # raw responder standing in for osd.1: speaks the KEX so the
    # sender proceeds, then captures the sealed payload verbatim
    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("127.0.0.1", ports[1]))
    lsock.listen(1)

    def sniff():
        from ceph_tpu.msg.secure import SecureConn
        conn, _ = lsock.accept()
        sc = SecureConn("cluster-key", initiator=False)
        captured["kex"] = recv_frame(conn)
        assert sc.ingest_kex(captured["kex"])
        send_frame(conn, sc.kex_frame())
        captured["frame"] = recv_frame(conn)
        done.set()
        conn.close()

    threading.Thread(target=sniff, daemon=True).start()
    net = TcpNet(addrs, secure_secret="cluster-key")
    ms = Messenger.create(net, "osd.0")
    ms.start()
    assert ms.connect("osd.1").send_message(OSDOp(oid="o", op="write",
                                                  data=marker))
    assert done.wait(10)
    assert marker not in captured["kex"]
    assert marker not in captured["frame"]
    ms.shutdown()
    lsock.close()


def test_secure_endpoints_interoperate_and_reject_plaintext():
    from ceph_tpu.msg.messages import OSDOp
    ports = pick_free_ports(2)
    addrs = {"osd.0": ("127.0.0.1", ports[0]),
             "osd.1": ("127.0.0.1", ports[1])}
    net = TcpNet(addrs, secure_secret="cluster-key")
    got = []
    ev = threading.Event()

    class D(Dispatcher):
        def ms_dispatch(self, msg):
            got.append(msg)
            ev.set()
            return True

        def ms_handle_reset(self, peer):
            pass

    a = Messenger.create(net, "osd.0")
    b = Messenger.create(net, "osd.1")
    b.add_dispatcher(D())
    a.add_dispatcher(D())
    a.start()
    b.start()
    assert a.connect("osd.1").send_message(
        OSDOp(oid="x", op="write", data=b"over the sealed wire"))
    assert ev.wait(10)
    assert got[0].data == b"over the sealed wire"
    # a plaintext (or wrong-key) frame into a secure listener is
    # dropped without dispatch
    ev.clear()
    got.clear()
    from ceph_tpu.msg.encoding import encode_message
    raw = socket.create_connection(addrs["osd.1"], timeout=5)
    send_frame(raw, encode_message(OSDOp(oid="evil", op="write")))
    assert not ev.wait(0.5)
    assert not got
    raw.close()
    a.shutdown()
    b.shutdown()


def test_secure_cluster_io():
    """Full mon+OSD cluster over sealed TCP frames, client included."""
    import os
    from ceph_tpu.client import Rados
    from ceph_tpu.mon.monitor import Monitor, build_initial
    from ceph_tpu.osd.daemon import OSDDaemon

    names = ["mon.0", "osd.0", "osd.1", "osd.2"]
    ports = pick_free_ports(len(names))
    addrs = {n: ("127.0.0.1", p) for n, p in zip(names, ports)}
    net = TcpNet(addrs, secure_secret="cluster-secret")
    m, w = build_initial(3, osds_per_host=1)
    mon = Monitor(net, rank=0, initial_map=m, initial_wrapper=w)
    mon.init()
    osds = [OSDDaemon(net, i, threaded=True) for i in range(3)]
    for d in osds:
        d.init()
    r = Rados(net, name="client.960", op_timeout=15.0)
    try:
        r.connect(30.0)
        import time
        t0 = time.monotonic()
        while time.monotonic() - t0 < 30:
            if sum(1 for o in range(3)
                   if r.objecter.osdmap.is_up(o)) == 3:
                break
            time.sleep(0.1)
        r.pool_create("sec", pg_num=8)
        io = r.open_ioctx("sec")
        payload = os.urandom(100_000)
        io.write_full("sealed", payload)
        assert io.read("sealed") == payload
    finally:
        r.shutdown()
        for d in osds:
            d.shutdown()
        mon.shutdown()


def test_onwire_compression_roundtrip():
    """msgr compression (ref: msgr v2 compression + the compressor
    registry): big compressible frames shrink on the wire; compression
    composes with secure mode (compress, then seal)."""
    from ceph_tpu.msg.messages import OSDOp
    for secure in (None, "cluster-key"):
        ports = pick_free_ports(2)
        addrs = {"osd.0": ("127.0.0.1", ports[0]),
                 "osd.1": ("127.0.0.1", ports[1])}
        net = TcpNet(addrs, secure_secret=secure, compress="zlib",
                     compress_min=1024)
        got = []
        ev = threading.Event()

        class D(Dispatcher):
            def ms_dispatch(self, msg):
                got.append(msg)
                ev.set()
                return True

            def ms_handle_reset(self, peer):
                pass

        a = Messenger.create(net, "osd.0")
        b = Messenger.create(net, "osd.1")
        b.add_dispatcher(D())
        a.add_dispatcher(D())
        a.start()
        b.start()
        payload = b"A" * 200_000        # highly compressible
        assert a.connect("osd.1").send_message(
            OSDOp(oid="big", op="write", data=payload))
        assert ev.wait(10)
        assert got[0].data == payload
        # small frames pass through uncompressed, still correct
        ev.clear()
        got.clear()
        assert a.connect("osd.1").send_message(
            OSDOp(oid="small", op="write", data=b"tiny"))
        assert ev.wait(10)
        assert got[0].data == b"tiny"
        a.shutdown()
        b.shutdown()


def test_compression_shrinks_wire_bytes():
    from ceph_tpu.msg.messages import OSDOp
    ports = pick_free_ports(2)
    addrs = {"osd.0": ("127.0.0.1", ports[0]),
             "osd.1": ("127.0.0.1", ports[1])}
    captured = {}
    done = threading.Event()
    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("127.0.0.1", ports[1]))
    lsock.listen(1)

    def sniff():
        conn, _ = lsock.accept()
        captured["frame"] = recv_frame(conn)
        done.set()
        conn.close()

    threading.Thread(target=sniff, daemon=True).start()
    net = TcpNet(addrs, compress="zlib", compress_min=1024)
    ms = Messenger.create(net, "osd.0")
    ms.start()
    payload = b"B" * 300_000
    assert ms.connect("osd.1").send_message(
        OSDOp(oid="z", op="write", data=payload))
    assert done.wait(10)
    assert len(captured["frame"]) < len(payload) // 10
    ms.shutdown()
    lsock.close()


def test_compressed_bomb_and_garbage_rejected():
    """A corrupt compressed frame must not kill the reader thread, and
    a decompression bomb must not inflate past the frame cap."""
    import pytest as _pytest
    import zlib
    from ceph_tpu import compressor
    from ceph_tpu.msg.messages import OSDOp
    from ceph_tpu.msg.tcp import MAX_FRAME
    # capped decompress refuses bombs
    bomb = compressor.compress(b"\0" * (2 << 20), "zlib")
    with _pytest.raises(ValueError):
        compressor.decompress(bomb, max_len=1 << 20)
    assert compressor.decompress(bomb, max_len=4 << 20) == \
        b"\0" * (2 << 20)
    # a garbage compressed frame drops the connection, not the thread
    ports = pick_free_ports(2)
    addrs = {"osd.0": ("127.0.0.1", ports[0]),
             "osd.1": ("127.0.0.1", ports[1])}
    net = TcpNet(addrs, compress="zlib", compress_min=64)
    got = []
    ev = threading.Event()

    class D(Dispatcher):
        def ms_dispatch(self, msg):
            got.append(msg)
            ev.set()
            return True

        def ms_handle_reset(self, peer):
            pass

    b = Messenger.create(net, "osd.1")
    b.add_dispatcher(D())
    b.start()
    raw = socket.create_connection(addrs["osd.1"], timeout=5)
    send_frame(raw, b"\x01" + b"ctpz\x01\x04zlib" + b"garbage!!")
    import time
    time.sleep(0.3)
    assert not got
    # the endpoint still serves well-formed peers afterwards
    a = Messenger.create(net, "osd.0")
    a.start()
    assert a.connect("osd.1").send_message(
        OSDOp(oid="ok", op="write", data=b"x" * 200))
    assert ev.wait(10)
    raw.close()
    a.shutdown()
    b.shutdown()


def test_unknown_compressor_fails_fast():
    import pytest as _pytest
    ports = pick_free_ports(1)
    with _pytest.raises(ValueError):
        Messenger.create(
            TcpNet({"osd.0": ("127.0.0.1", ports[0])},
                   compress="zstd"), "osd.0")

# --------------------------------------------- per-session keys (r4)

def _pair(secret="shared-cluster-secret"):
    from ceph_tpu.msg.secure import SecureConn
    a = SecureConn(secret, initiator=True)
    b = SecureConn(secret, initiator=False)
    assert b.ingest_kex(a.kex_frame())
    assert a.ingest_kex(b.kex_frame())
    return a, b


def test_per_session_keys_isolate_sessions():
    """VERDICT r3 #4: two sessions under the SAME cluster secret are
    mutually non-decryptable — a compromised daemon (or any client
    holding the secret) can no longer read other sessions' traffic."""
    a1, b1 = _pair()
    a2, b2 = _pair()
    frame = a1.seal(b"session-one confidential bytes")
    assert b1.open(frame) == b"session-one confidential bytes"
    # the other session (same secret!) cannot open a replica of it
    frame2 = a1.seal(b"again")
    assert b2.open(frame2) is None
    assert a2.open(frame2) is None
    # nor can the sender's own receive direction (direction split)
    frame3 = a1.seal(b"direction test")
    assert a1.open(frame3) is None


def test_replay_and_reorder_rejected():
    a, b = _pair()
    f1 = a.seal(b"one")
    f2 = a.seal(b"two")
    assert b.open(f1) == b"one"
    assert b.open(f1) is None          # replay
    assert b.open(f2) == b"two"
    a2, b2 = _pair()
    g1, g2 = a2.seal(b"x"), a2.seal(b"y")
    assert b2.open(g2) is None         # out of order (counter strict)
    assert b2.open(g1) == b"x"


def test_kex_requires_cluster_secret():
    """An outsider cannot MITM: its KEX fails the cluster-secret MAC;
    degenerate DH shares are rejected too."""
    from ceph_tpu.msg.secure import (SecureConn, _DH_P, _PUB_LEN,
                                     TAG_LEN)
    import hashlib
    import hmac as _hmac
    good = SecureConn("right-secret", initiator=False)
    evil = SecureConn("WRONG-secret", initiator=True)
    assert not good.ingest_kex(evil.kex_frame())
    # degenerate share (pub=1) signed with the right secret
    body = b"KEX1" + b"\x00" * 16 + (1).to_bytes(_PUB_LEN, "big")
    mac = _hmac.new(b"right-secret", b"ms-kex|" + body,
                    hashlib.sha256).digest()[:TAG_LEN]
    assert not good.ingest_kex(body + mac)


def test_rekey_rotates_connection_keys(monkeypatch):
    """Past REKEY_FRAMES the transport reconnects: a fresh KEX means
    fresh keys, and traffic keeps flowing across the rotation."""
    import ceph_tpu.msg.tcp as tcpmod
    from ceph_tpu.msg.messages import OSDOp
    monkeypatch.setattr("ceph_tpu.msg.secure.REKEY_FRAMES", 5)
    ports = pick_free_ports(2)
    addrs = {"osd.0": ("127.0.0.1", ports[0]),
             "osd.1": ("127.0.0.1", ports[1])}
    net = TcpNet(addrs, secure_secret="k")
    netb = TcpNet(addrs, secure_secret="k")
    got = []
    ev = threading.Event()

    class D(Dispatcher):
        def ms_dispatch(self, msg):
            got.append(msg)
            if len(got) >= 12:
                ev.set()
            return True

        def ms_handle_reset(self, peer):
            pass

    a = Messenger.create(net, "osd.0")
    b = Messenger.create(netb, "osd.1")
    b.add_dispatcher(D())
    a.add_dispatcher(D())
    a.start()
    b.start()
    sessions_seen = set()
    for i in range(12):
        assert a.connect("osd.1").send_message(
            OSDOp(oid=f"o{i}", op="write", data=b"d" * 64))
        for s in list(a._sessions.values()):
            sessions_seen.add(id(s))
    assert ev.wait(10)
    assert len(got) == 12
    assert len(sessions_seen) >= 2, "rekey never rotated the session"
    a.shutdown()
    b.shutdown()
