"""ConfigMonitor: centralized config through the mon quorum
(ref: src/mon/ConfigMonitor.cc, src/messages/MConfig.h)."""
import pytest

from ceph_tpu.common.options import global_config
from ceph_tpu.testing import MiniCluster


@pytest.fixture()
def cluster():
    c = MiniCluster(n_osd=2, threaded=True)
    c.wait_all_up()
    yield c, c.rados()
    c.shutdown()


def test_set_get_dump_rm(cluster):
    _, r = cluster
    rc, outs, _ = r.mon_command({"prefix": "config set", "who": "osd",
                                 "name": "osd_heartbeat_interval",
                                 "value": "2.5"})
    assert rc == 0, outs
    rc, _, val = r.mon_command({"prefix": "config get", "who": "osd.1",
                                "name": "osd_heartbeat_interval"})
    assert rc == 0 and val == "2.5"
    # precedence: entity beats type beats global
    r.mon_command({"prefix": "config set", "who": "global",
                   "name": "ms_type", "value": "local"})
    r.mon_command({"prefix": "config set", "who": "osd.1",
                   "name": "osd_heartbeat_interval", "value": "9"})
    rc, _, merged = r.mon_command({"prefix": "config get",
                                   "who": "osd.1"})
    assert merged["osd_heartbeat_interval"] == "9"
    assert merged["ms_type"] == "local"
    rc, _, other = r.mon_command({"prefix": "config get",
                                  "who": "osd.0"})
    assert other["osd_heartbeat_interval"] == "2.5"
    rc, _, dump = r.mon_command({"prefix": "config dump"})
    assert dump["osd"]["osd_heartbeat_interval"] == "2.5"
    # rm
    r.mon_command({"prefix": "config rm", "who": "osd.1",
                   "name": "osd_heartbeat_interval"})
    rc, _, merged = r.mon_command({"prefix": "config get",
                                   "who": "osd.1"})
    assert merged["osd_heartbeat_interval"] == "2.5"
    rc, outs, _ = r.mon_command({"prefix": "config get", "who": "osd.1",
                                 "name": "nope_not_set"})
    assert rc == -2


def test_config_pushed_to_osds(cluster):
    """A committed config set reaches subscribed daemons and applies
    to their live options registry."""
    _, r = cluster
    cfg = global_config()
    old = cfg["osd_heartbeat_interval"]
    try:
        rc, _, _ = r.mon_command({"prefix": "config set", "who": "osd",
                                  "name": "osd_heartbeat_interval",
                                  "value": "3.25"})
        assert rc == 0
        import time
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                cfg["osd_heartbeat_interval"] != 3.25:
            time.sleep(0.05)
        assert cfg["osd_heartbeat_interval"] == 3.25
    finally:
        cfg.set("osd_heartbeat_interval", old)


def test_config_rm_reverts_on_daemons(cluster):
    """`config rm` must revert the live value on running daemons, not
    just stop future pushes (ref: md_config_t::set_mon_vals)."""
    _, r = cluster
    cfg = global_config()
    default = cfg.schema["osd_heartbeat_interval"].default
    import time
    r.mon_command({"prefix": "config set", "who": "osd",
                   "name": "osd_heartbeat_interval", "value": "2.25"})
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and \
            cfg["osd_heartbeat_interval"] != 2.25:
        time.sleep(0.05)
    assert cfg["osd_heartbeat_interval"] == 2.25
    r.mon_command({"prefix": "config rm", "who": "osd",
                   "name": "osd_heartbeat_interval"})
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and \
            cfg["osd_heartbeat_interval"] != default:
        time.sleep(0.05)
    assert cfg["osd_heartbeat_interval"] == default


def test_config_survives_quorum_failover():
    """Values committed through a 3-mon quorum survive killing the
    leader — the new leader serves the same committed state."""
    c = MiniCluster(n_osd=2, n_mon=3, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        rc, outs, _ = r.mon_command({"prefix": "config set",
                                     "who": "global",
                                     "name": "mon_lease",
                                     "value": "7"})
        assert rc == 0, outs
        leader = c.leader()
        assert leader is not None
        c.kill_mon(leader.rank)
        import time
        deadline = time.monotonic() + 30
        val = None
        while time.monotonic() < deadline:
            try:
                rc, _, val = r.objecter.mon_command(
                    {"prefix": "config get", "who": "mon.1",
                     "name": "mon_lease"}, timeout=5.0)
                if rc == 0:
                    break
            except TimeoutError:
                pass
            time.sleep(0.25)
        assert val == "7"
    finally:
        c.shutdown()
