"""GF(2^8) arithmetic core (numpy, CPU reference oracle).

Field: GF(2^8) with the primitive polynomial x^8+x^4+x^3+x^2+1 (0x11d),
the field used by both ISA-L (`gf_mul` tables) and gf-complete's default
w=8 field — so all coding matrices and parity bytes here are in the same
field as the reference plugins (ref: src/erasure-code/isa/ErasureCodeIsa.cc,
src/erasure-code/jerasure/ErasureCodeJerasure.cc).

Everything in this module is plain numpy and serves as the byte-exact CPU
oracle against which the TPU (JAX/Pallas) kernels are verified.
"""
from __future__ import annotations

import functools

import numpy as np

GF_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1
GF_ORDER = 256


@functools.lru_cache(maxsize=None)
def _tables() -> tuple[np.ndarray, np.ndarray]:
    """(log, antilog) tables for generator 2 over poly 0x11d.

    antilog[i] = 2^i for i in [0, 255) (period 255); log[antilog[i]] = i.
    log[0] is invalid and set to 512 so table users can detect it.
    """
    antilog = np.zeros(512, dtype=np.int32)  # doubled to skip the % 255
    log = np.full(256, 512, dtype=np.int32)
    x = 1
    for i in range(255):
        antilog[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    antilog[255:510] = antilog[0:255]
    return log, antilog


@functools.lru_cache(maxsize=None)
def mul_table() -> np.ndarray:
    """Full 256x256 GF(2^8) multiplication table (uint8)."""
    log, antilog = _tables()
    a = np.arange(256)
    s = log[a][:, None] + log[a][None, :]
    out = antilog[np.minimum(s, 510)].astype(np.uint8)
    out[0, :] = 0
    out[:, 0] = 0
    return out


@functools.lru_cache(maxsize=None)
def inv_table() -> np.ndarray:
    """Multiplicative inverses; inv[0] = 0 (matching ISA-L gf_inv(0) wrap)."""
    log, antilog = _tables()
    inv = np.zeros(256, dtype=np.uint8)
    inv[1:] = antilog[255 - log[np.arange(1, 256)]].astype(np.uint8)
    return inv


def gf_mul(a: int, b: int) -> int:
    return int(mul_table()[a & 0xFF, b & 0xFF])


def gf_inv(a: int) -> int:
    return int(inv_table()[a & 0xFF])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    return gf_mul(a, gf_inv(b))


def gf_pow(a: int, n: int) -> int:
    r = 1
    for _ in range(n):
        r = gf_mul(r, a)
    return r


# ---------------------------------------------------------------------------
# Vectorized block math (the CPU oracle for encode/decode)
# ---------------------------------------------------------------------------

def gf_matmul_bytes(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """(r x k) GF matrix times (k x n) byte block -> (r x n) bytes.

    out[i, :] = XOR_j mat[i, j] * data[j, :].  This is exactly ISA-L's
    ec_encode_data semantics (ref: src/erasure-code/isa/ErasureCodeIsa.cc:129)
    with mat = the coding submatrix.
    """
    mat = np.asarray(mat, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    r, k = mat.shape
    assert data.shape[0] == k, (mat.shape, data.shape)
    MUL = mul_table()
    out = np.zeros((r, data.shape[1]), dtype=np.uint8)
    for j in range(k):  # loop k (small); vector ops over n (large)
        out ^= MUL[mat[:, j][:, None], data[j][None, :]]
    return out


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Small dense GF matrix product (r x k) @ (k x c)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    MUL = mul_table()
    prod = MUL[a[:, :, None], b[None, :, :]]  # (r, k, c)
    return np.bitwise_xor.reduce(prod, axis=1)


def gf_invert_matrix(m: np.ndarray) -> np.ndarray | None:
    """Gauss-Jordan inversion over GF(2^8); None if singular.

    Mirrors ISA-L gf_invert_matrix semantics (used by the isa plugin decode,
    ref: src/erasure-code/isa/ErasureCodeIsa.cc:275).
    """
    m = np.array(m, dtype=np.uint8, copy=True)
    n = m.shape[0]
    assert m.shape == (n, n)
    MUL = mul_table()
    INV = inv_table()
    out = np.eye(n, dtype=np.uint8)
    for i in range(n):
        # pivot: swap in a lower row if the diagonal is zero
        if m[i, i] == 0:
            rows = np.nonzero(m[i + 1:, i])[0]
            if rows.size == 0:
                return None
            j = i + 1 + rows[0]
            m[[i, j]] = m[[j, i]]
            out[[i, j]] = out[[j, i]]
        piv = INV[m[i, i]]
        m[i] = MUL[piv, m[i]]
        out[i] = MUL[piv, out[i]]
        mask = np.ones(n, dtype=bool)
        mask[i] = False
        factors = m[mask, i]
        m[mask] ^= MUL[factors[:, None], m[i][None, :]]
        out[mask] ^= MUL[factors[:, None], out[i][None, :]]
    return out


# ---------------------------------------------------------------------------
# Coding-matrix generation (matching the reference plugins' constructions)
# ---------------------------------------------------------------------------

def isa_rs_matrix(k: int, m: int) -> np.ndarray:
    """ISA-L gf_gen_rs_matrix: (k+m) x k, identity on top, coding row
    i (i >= k) = [gen^0, gen^1, ..., gen^(k-1)] with gen = 2^(i-k).

    The first coding row is all-ones, which is why the isa plugin has an XOR
    fast path for single data/first-parity erasures
    (ref: src/erasure-code/isa/ErasureCodeIsa.cc:196-216,385).
    """
    a = np.zeros((k + m, k), dtype=np.uint8)
    a[:k] = np.eye(k, dtype=np.uint8)
    MUL = mul_table()
    gen = 1
    for i in range(k, k + m):
        p = 1
        for j in range(k):
            a[i, j] = p
            p = int(MUL[p, gen])
        gen = int(MUL[gen, 2])
    return a


def isa_cauchy_matrix(k: int, m: int) -> np.ndarray:
    """ISA-L gf_gen_cauchy1_matrix: identity on top; coding row i, col j =
    1 / (i ^ j) for i in [k, k+m) (ref: ErasureCodeIsa.cc:387)."""
    a = np.zeros((k + m, k), dtype=np.uint8)
    a[:k] = np.eye(k, dtype=np.uint8)
    INV = inv_table()
    for i in range(k, k + m):
        for j in range(k):
            a[i, j] = INV[i ^ j]
    return a


def vandermonde_matrix(rows: int, cols: int) -> np.ndarray:
    """V[i][j] = i^j in GF(2^8) (0^0 = 1)."""
    MUL = mul_table()
    v = np.zeros((rows, cols), dtype=np.uint8)
    v[:, 0] = 1
    for i in range(rows):
        for j in range(1, cols):
            v[i, j] = MUL[v[i, j - 1], i]
    return v


def jerasure_vandermonde_coding_matrix(k: int, m: int) -> np.ndarray:
    """Systematic Vandermonde coding rows, jerasure reed_sol_van style.

    jerasure builds V[i][j] = i^j over k+m rows and systematizes the top
    k x k block to identity with column elementary operations
    (reed_sol_vandermonde_coding_matrix, used by the jerasure plugin at
    ref: src/erasure-code/jerasure/ErasureCodeJerasure.cc:205).  Column
    operations that reduce the top block to I amount to right-multiplying by
    inv(V[:k]), so the result is canonically W = V @ inv(V[:k]); the coding
    matrix is its bottom m rows.
    """
    v = vandermonde_matrix(k + m, k)
    top_inv = gf_invert_matrix(v[:k])
    assert top_inv is not None
    return gf_matmul(v[k:], top_inv)


def jerasure_r6_coding_matrix(k: int) -> np.ndarray:
    """RAID-6 rows: P = all ones, Q = [1, 2, 4, ... 2^(k-1)]
    (jerasure reed_sol_r6_coding_matrix; plugin technique reed_sol_r6_op,
    ref: src/erasure-code/jerasure/ErasureCodeJerasure.h:84)."""
    MUL = mul_table()
    mat = np.zeros((2, k), dtype=np.uint8)
    mat[0] = 1
    p = 1
    for j in range(k):
        mat[1, j] = p
        p = int(MUL[p, 2])
    return mat


def cauchy_original_coding_matrix(k: int, m: int) -> np.ndarray:
    """jerasure cauchy_original_coding_matrix: row i, col j = 1/(i ^ (m+j))
    (technique cauchy_orig, ref: ErasureCodeJerasure.cc:324)."""
    INV = inv_table()
    a = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            a[i, j] = INV[i ^ (m + j)]
    return a


def gf_bitmatrix_ones(e: int) -> int:
    """Number of 1 bits in the 8x8 GF(2)-companion matrix of 'multiply by e'
    (jerasure's cost metric for cauchy_good matrix improvement)."""
    MUL = mul_table()
    return sum(int(bin(int(MUL[e, 1 << c])).count("1")) for c in range(8))


def cauchy_good_coding_matrix(k: int, m: int) -> np.ndarray:
    """jerasure cauchy_good_general_coding_matrix: start from the original
    Cauchy matrix, then improve it (divide each column by its row-0 element
    so row 0 is all ones; then divide each later row by the element whose
    choice minimizes the total bitmatrix ones-count of the row)
    (technique cauchy_good, ref: ErasureCodeJerasure.cc:334)."""
    a = cauchy_original_coding_matrix(k, m)
    MUL = mul_table()
    INV = inv_table()
    # column normalize: row 0 -> all ones
    for j in range(k):
        d = INV[a[0, j]]
        a[:, j] = MUL[d, a[:, j]]
    # row improve
    for i in range(1, m):
        best_div, best_cost = 1, None
        for e in sorted(set(int(x) for x in a[i])):
            d = INV[e]
            cost = sum(gf_bitmatrix_ones(int(MUL[d, x])) for x in a[i])
            if best_cost is None or cost < best_cost:
                best_cost, best_div = cost, d
        a[i] = MUL[best_div, a[i]]
    return a


# ---------------------------------------------------------------------------
# GF(2) companion-bitmatrix expansion (shared by TPU kernels and jerasure-
# style bitmatrix scheduling)
# ---------------------------------------------------------------------------

def expand_to_bitmatrix(mat: np.ndarray) -> np.ndarray:
    """(r x k) byte matrix -> (8r x 8k) GF(2) bit matrix B such that
    byte-matmul over GF(2^8) == bit-matmul over GF(2) on bit-planes.

    B[8i+t, 8j+c] = bit t of (mat[i,j] * x^c).  This is also jerasure's
    jerasure_matrix_to_bitmatrix layout (transposed per-cell), and is the
    exact linear-algebra form the TPU kernel runs on the MXU.
    """
    mat = np.asarray(mat, dtype=np.uint8)
    r, k = mat.shape
    MUL = mul_table()
    shifted = MUL[mat[:, :, None], (1 << np.arange(8))[None, None, :]]  # (r,k,8) bytes
    bits = (shifted[:, :, None, :] >> np.arange(8)[None, None, :, None]) & 1  # (r,k,8t,8c)
    return bits.transpose(0, 2, 1, 3).reshape(8 * r, 8 * k).astype(np.uint8)
