"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/collective
tests run on a virtual 8-device CPU platform (the driver separately
dry-run-compiles the multi-chip path via __graft_entry__.dryrun_multichip).

Note: the axon sitecustomize sets jax.config jax_platforms='axon,cpu' at
interpreter start, so the JAX_PLATFORMS env var alone is NOT enough — we
must override the config value before any backend initializes.
"""
import os

# Every tier-1 run is a deadlock-sanitizer run: lockdep ON before any
# ceph_tpu import, because make_lock reads the option at CONSTRUCTION
# time (module-level locks are built at import).  The env layer also
# propagates to subprocess daemons (tools/daemon_main), so TCP
# multi-process tests run order-checked too.  A lock-order cycle
# anywhere under test raises LockOrderError on the FIRST interleaving
# that could deadlock — not the unlucky run that does (ref:
# src/common/lockdep.cc).  Force-set (not setdefault): an ambient
# CEPH_TPU_LOCKDEP=0 in a dev shell must not silently turn the
# sanitizer off for the whole suite.
os.environ["CEPH_TPU_LOCKDEP"] = "1"

# ... and every tier-1 run is a data-race sanitizer run: racecheck ON
# before any ceph_tpu import (shared_state()/RaceTracked classes
# register at class creation; enable() retro-instruments, but the env
# must be set before global_config() first resolves).  Attribute
# accesses on instrumented daemon structures intersect Eraser-style
# candidate locksets against lockdep's per-thread held set and raise
# RaceError when no common lock protects a write-shared attribute
# (see ceph_tpu/common/racecheck.py).  Propagates to subprocess
# daemons through the env layer like lockdep.  Force-set for the
# same reason as lockdep above.
os.environ["CEPH_TPU_RACECHECK"] = "1"

# ... and every tier-1 run is an error-path coverage run: errcheck ON
# so the import hook can instrument ceph_tpu modules as tests pull
# them in — every except handler entered anywhere in the suite bumps
# a (module, line, exception-type) counter, and scripts/errcov_smoke.py
# turns the same machinery into the published ERRCOV artifact.  The
# env layer propagates to subprocess daemons (tools/daemon_main) like
# the other sanitizers.  Force-set for the same reason as lockdep.
os.environ["CEPH_TPU_ERRCHECK"] = "1"

# ... and every tier-1 run is a device-contract sanitizer run too:
# jaxguard ON before any ceph_tpu import, because enable() wraps
# jax.jit and module-level jit wrappers are built at import.  A jit
# callsite that recompiles an already-compiled signature raises
# RecompileError at the offending call, and the EC/placement entry
# points run under jax.transfer_guard('disallow') — an unintended
# host<->device transfer is an error, not a silent 2x slowdown
# (see ceph_tpu/common/jaxguard.py).  Force-set for the same reason
# as lockdep above.
os.environ["CEPH_TPU_JAXGUARD"] = "1"

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", jax.default_backend()
assert len(jax.devices()) == 8, jax.devices()

# arm errcheck FIRST among the ceph_tpu imports: the import hook
# only instruments modules imported AFTER it installs, so it must be
# live before jaxguard/racecheck (and everything they pull) load
from ceph_tpu.common import errcheck  # noqa: E402

assert errcheck.enable_if_configured(), "CEPH_TPU_ERRCHECK=1 set above"

# arm jaxguard AFTER the backend asserts (its own jit probes must not
# count) and BEFORE any ceph_tpu import builds a jit wrapper
from ceph_tpu.common import jaxguard  # noqa: E402

assert jaxguard.enable_if_configured(), "CEPH_TPU_JAXGUARD=1 set above"

# arm racecheck before any ceph_tpu daemon module is imported: classes
# already registered instrument now, later registrations instrument at
# class creation
from ceph_tpu.common import racecheck  # noqa: E402

assert racecheck.enable_if_configured(), "CEPH_TPU_RACECHECK=1 set above"


def _kill_stray_daemons() -> int:
    """Hermetic-suite guard (VERDICT r3 weak #8): daemon_main
    processes leaked by an earlier crashed/killed run keep their TCP
    ports bound and wedge this run's multiprocess tests.  Only
    ORPHANS (reparented to init) are killed — a concurrent pytest
    session's live daemons still have their live parent."""
    import signal
    import subprocess
    try:
        with open("/proc/1/cmdline", "rb") as f:
            init_cmd = f.read().decode(errors="replace")
    except OSError:
        init_cmd = ""
    if "python" in init_cmd or "pytest" in init_cmd:
        # containerized CI with pytest as PID 1: its live daemons
        # legitimately have PPid 1 — cannot tell leaks apart, skip
        return 0
    try:
        out = subprocess.run(
            ["pgrep", "-f", "ceph_tpu.tools.daemon_main"],
            capture_output=True, text=True, timeout=10).stdout
    except Exception:
        return 0
    killed = 0
    for pid_s in out.split():
        try:
            pid = int(pid_s)
            with open(f"/proc/{pid}/status") as f:
                ppid = next((int(ln.split()[1]) for ln in f
                             if ln.startswith("PPid:")), -1)
            if ppid != 1:
                continue            # parent alive: not a leak
            os.kill(pid, signal.SIGKILL)
            killed += 1
        except (ValueError, OSError, StopIteration):
            pass
    return killed


_stray = _kill_stray_daemons()
if _stray:
    import sys
    print(f"conftest: killed {_stray} stray daemon_main process(es)",
          file=sys.stderr)
