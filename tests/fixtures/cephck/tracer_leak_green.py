"""green: traced fn returns; storage happens outside the trace."""
import jax
import jax.numpy as jnp


@jax.jit
def encode(v):
    return jnp.matmul(v, v)


class Coder:
    def __init__(self):
        self.last = None

    def run(self, v):
        out = encode(v)
        self.last = out             # outside the jit boundary: fine
        return out
