"""ObjectCacher: a write-back object extent cache shared by librbd
images and CephFS file handles (ref: src/osdc/ObjectCacher.{h,cc} —
the BufferHead extent cache both libraries mount on top of the
Objecter; VERDICT r3 #6).

Model (page-granular BufferHeads):

* Each cached object holds fixed-size **pages** (default 64 KiB) with
  a valid set and a dirty set.  A partial-page write to an uncached
  page write-allocates: the page is first read from the backing store
  (read-modify-write), so flushing always writes fully-valid pages —
  flushing a partially-known page would overwrite backing bytes that
  were never cached.
* **Write-back**: writes land in pages and return; `flush()` pushes
  dirty pages (consecutive runs coalesced into one backing write) in
  object order.  Exceeding `max_dirty` triggers an inline flush of
  the oldest dirty object (the reference's dirty/tx throttle).
* **Bounded memory**: an LRU across objects; past `max_size`, clean
  pages of the least-recently-used objects are evicted (dirty pages
  flush first).
* **Coherence contract**: single writer per object range — exactly
  what the callers' concurrency machinery guarantees (librbd's
  exclusive lock, CephFS's CAP_EXCL/CAP_CACHE capabilities).  Cap
  revocation / lock release MUST `flush()` + `invalidate()` (the
  flush-ordering obligation ObjectCacher places on its users).

The backing store is abstracted as two callables, so the same cacher
serves rbd (object reads with parent fall-through + copyup writes)
and cephfs (striped file objects):

    read_fn(oid, off, length) -> bytes   # short/empty = sparse zeros
    write_fn(oid, off, data)  -> None

An optional third callable batches cold fills for `read_many`:

    read_many_fn([(oid, off, length), ...]) -> [bytes, ...]

Readahead is a pluggable **policy** per cacher (selectable per serve
handle): `checkpoint` is the historical sequential-doubling window,
`kvcache` is the random-page policy — no readahead, pages pinned /
refcounted by the caller, LRU eviction only among unpinned pages.
"""
from __future__ import annotations

import threading

from ..common.lockdep import make_lock
from collections import OrderedDict
from typing import Callable


class _CachedObject:
    __slots__ = ("pages", "valid", "dirty", "vlen", "seq_end",
                 "ra_window", "pins")

    def __init__(self):
        self.pages: dict[int, bytearray] = {}
        self.valid: set[int] = set()
        self.dirty: set[int] = set()
        #: per-page count of bytes known to exist in the backing (from
        #: the fill read) or written through this cache.  Flushing
        #: truncates a run's FINAL page to this, so a 10-byte file
        #: never grows to a 64 KiB backing object of trailing zeros
        #: (the reference's BufferHeads are byte-granular for the same
        #: reason; ref: src/osdc/ObjectCacher.h bh lengths)
        self.vlen: dict[int, int] = {}
        #: sequential-read detector (ref: src/common/Readahead.cc):
        #: where the last read ended, and the current readahead window
        self.seq_end: int = -1
        self.ra_window: int = 0
        #: page -> pin refcount; pinned pages never evict (kvcache
        #: policy: a page handed to attention kernels must stay
        #: resident until the caller unpins it)
        self.pins: dict[int, int] = {}


class ReadaheadPolicy:
    """Per-read fill-overshoot decision.  `on_read` sees the request
    and the object's detector state and returns how many bytes PAST
    the request the fill may fetch (0 = exactly the request)."""
    name = "none"

    def on_read(self, o: _CachedObject, off: int, length: int,
                page: int, max_readahead: int) -> int:
        o.seq_end = off + length
        return 0


class CheckpointReadahead(ReadaheadPolicy):
    """Sequential-resume streaming (checkpoint shards read front to
    back): a read starting where the last one ended doubles the
    window up to max_readahead; any random jump resets it — so
    amplification only ever follows a proven sequential pattern
    (ref: src/common/Readahead.cc update)."""
    name = "checkpoint"

    def on_read(self, o, off, length, page, max_readahead):
        if max_readahead and off == o.seq_end:
            o.ra_window = min(max(o.ra_window * 2, page),
                              max_readahead)
        else:
            o.ra_window = 0
        o.seq_end = off + length
        return o.ra_window


class KVCacheReadahead(ReadaheadPolicy):
    """Random-page KV-cache gets: page ids arrive in attention order,
    not address order, so readahead is pure waste — never overshoot.
    Residency is the caller's business via pin()/unpin(); eviction
    runs LRU among the unpinned only."""
    name = "kvcache"

    def on_read(self, o, off, length, page, max_readahead):
        o.seq_end = off + length
        o.ra_window = 0
        return 0


READAHEAD_POLICIES: dict[str, type[ReadaheadPolicy]] = {
    "none": ReadaheadPolicy,
    "checkpoint": CheckpointReadahead,
    "kvcache": KVCacheReadahead,
}


class ObjectCacher:
    def __init__(self, read_fn: Callable, write_fn: Callable,
                 max_dirty: int = 8 << 20, max_size: int = 32 << 20,
                 page: int = 1 << 16, max_readahead: int = 512 << 10,
                 policy: "ReadaheadPolicy | str" = "checkpoint",
                 read_many_fn: Callable | None = None):
        self._read = read_fn
        self._write = write_fn
        #: batched cold-fill: read_many() hands ALL missing runs of a
        #: wave to this in one call (the serve store wires the
        #: objecter's parallel aio fan-out here); absent, runs fill
        #: one read_fn call each
        self._read_many = read_many_fn
        self.max_dirty = max_dirty
        self.max_size = max_size
        self.page = page
        #: sequential readahead cap (ref: rbd_readahead_max_bytes /
        #: ObjectCacher's max_readahead); 0 disables
        self.max_readahead = max_readahead
        if isinstance(policy, str):
            policy = READAHEAD_POLICIES[policy]()
        self.policy = policy
        self._objs: "OrderedDict[str, _CachedObject]" = OrderedDict()
        self._lock = make_lock("osdc.object_cacher")
        # O(1) accounting: page counts maintained at every transition
        # (a per-write full scan would sit on the hot path)
        self._n_pages = 0
        self._n_dirty = 0
        self.stats = {"hit": 0, "miss": 0, "flush_writes": 0,
                      "write_back": 0, "evicted_pages": 0,
                      "readahead_pages": 0}

    # -- accounting -----------------------------------------------------
    def dirty_bytes(self) -> int:
        return self._n_dirty * self.page

    def cached_bytes(self) -> int:
        return self._n_pages * self.page

    # -- internals ------------------------------------------------------
    def _obj(self, oid: str) -> _CachedObject:
        o = self._objs.get(oid)
        if o is None:
            o = self._objs[oid] = _CachedObject()
        self._objs.move_to_end(oid)          # LRU touch
        return o

    def _install(self, o: _CachedObject, p: int,
                 buf: bytearray, vlen: int = 0) -> None:
        if p not in o.valid:
            self._n_pages += 1
        o.pages[p] = buf
        o.valid.add(p)
        o.vlen[p] = max(o.vlen.get(p, 0), vlen)

    def _fill_page(self, oid: str, o: _CachedObject, p: int) -> None:
        """Write-allocate: fetch the page so a later flush writes only
        fully-valid bytes (short backing reads zero-fill = sparse)."""
        if p in o.valid:
            return
        data = self._read(oid, p * self.page, self.page) or b""
        buf = bytearray(self.page)
        buf[:len(data)] = data
        self._install(o, p, buf, vlen=len(data))

    def _fill_span(self, oid: str, o: _CachedObject,
                   pages: list[int]) -> None:
        """Cold-read fill: ONE backing read spanning the whole missing
        window, sliced into pages — per-page reads would serialize a
        cold object read into dozens of round-trips (the aio fan-out
        the uncached path had).  Already-valid pages (possibly dirty)
        are never overwritten."""
        missing = [p for p in pages if p not in o.valid]
        if not missing:
            return
        lo, hi = min(missing), max(missing)
        data = self._read(oid, lo * self.page,
                          (hi - lo + 1) * self.page) or b""
        for p in range(lo, hi + 1):
            if p in o.valid:
                continue
            base = (p - lo) * self.page
            buf = bytearray(self.page)
            chunk = data[base:base + self.page]
            buf[:len(chunk)] = chunk
            self._install(o, p, buf, vlen=len(chunk))

    def _page_range(self, off: int, length: int):
        return range(off // self.page,
                     (off + length - 1) // self.page + 1)

    # -- data path ------------------------------------------------------
    def read(self, oid: str, off: int, length: int) -> bytes:
        if length <= 0:
            return b""
        with self._lock:
            o = self._obj(oid)
            pages = list(self._page_range(off, length))
            # the policy decides the fill overshoot — not the returned
            # bytes — past the request (checkpoint: sequential-doubling
            # window per src/common/Readahead.cc; kvcache/none: 0)
            overshoot = self.policy.on_read(o, off, length, self.page,
                                            self.max_readahead)
            fill_pages = pages
            if overshoot:
                fill_pages = list(self._page_range(
                    off, length + overshoot))
            if all(p in o.valid for p in pages):
                self.stats["hit"] += 1
            else:
                self.stats["miss"] += 1
                # count only overshoot pages the fill actually
                # fetches — a full hit (or an overshoot into already-
                # cached pages) reads nothing ahead
                self.stats["readahead_pages"] += sum(
                    1 for p in fill_pages[len(pages):]
                    if p not in o.valid)
                self._fill_span(oid, o, fill_pages)
            out = bytearray()
            for p in pages:
                out += o.pages[p]
            base = off - pages[0] * self.page
            self._maybe_evict()
            return bytes(out[base:base + length])

    def read_many(self, reqs: list[tuple[str, int, int]]
                  ) -> list[bytes]:
        """Batched multi-range read: the whole page-fetch wave hits
        the cache under ONE lock acquisition.  Missing pages across
        all requests are unioned per object, grouped into contiguous
        runs, and fetched in a single read_many_fn wave (per-run
        read_fn calls when no batcher is wired).  Results come back
        in request order.

        Accounting: one hit/miss per request (a request whose pages
        arrive via ANOTHER request's fill in the same batch is still
        a miss — it needed backing bytes); `readahead_pages` counts
        only policy-overshoot pages no request in the batch asked
        for, so a page "prefetched" for a sibling request is demand,
        not readahead."""
        if not reqs:
            return []
        with self._lock:
            plans = []          # (oid, o, pages, off, length)
            need: dict[str, set[int]] = {}     # demand pages per oid
            fill: dict[str, set[int]] = {}     # demand + overshoot
            for oid, off, length in reqs:
                if length <= 0:
                    plans.append((oid, None, [], off, length))
                    continue
                o = self._obj(oid)
                pages = list(self._page_range(off, length))
                overshoot = self.policy.on_read(
                    o, off, length, self.page, self.max_readahead)
                plans.append((oid, o, pages, off, length))
                need.setdefault(oid, set()).update(pages)
                fill.setdefault(oid, set()).update(pages)
                if overshoot:
                    fill[oid].update(self._page_range(
                        off, length + overshoot))
            # hit/miss judged against pre-fill validity
            for oid, o, pages, _, length in plans:
                if length <= 0:
                    continue
                key = "hit" if all(p in o.valid for p in pages) \
                    else "miss"
                self.stats[key] += 1
            # readahead = overshoot pages nobody demanded, not yet
            # cached, that the fill will actually fetch
            for oid, want in fill.items():
                o = self._objs[oid]
                self.stats["readahead_pages"] += sum(
                    1 for p in want - need.get(oid, set())
                    if p not in o.valid)
            # contiguous missing runs per object -> one backing wave
            fetches: list[tuple[str, int, int]] = []
            runs: list[tuple[str, int, int]] = []   # (oid, lo, n)
            for oid, want in fill.items():
                o = self._objs[oid]
                missing = sorted(p for p in want if p not in o.valid)
                lo = prev = None
                for p in missing + [None]:
                    if lo is not None and (p is None or p != prev + 1):
                        runs.append((oid, lo, prev - lo + 1))
                        fetches.append((oid, lo * self.page,
                                        (prev - lo + 1) * self.page))
                        lo = None
                    if p is not None:
                        if lo is None:
                            lo = p
                        prev = p
            if fetches:
                if self._read_many is not None:
                    datas = self._read_many(fetches)
                else:
                    datas = [self._read(oid, off, ln) or b""
                             for oid, off, ln in fetches]
                for (oid, lo, n), data in zip(runs, datas):
                    data = data or b""
                    o = self._objs[oid]
                    for p in range(lo, lo + n):
                        if p in o.valid:
                            continue
                        base = (p - lo) * self.page
                        buf = bytearray(self.page)
                        chunk = data[base:base + self.page]
                        buf[:len(chunk)] = chunk
                        self._install(o, p, buf, vlen=len(chunk))
            out: list[bytes] = []
            for oid, o, pages, off, length in plans:
                if length <= 0:
                    out.append(b"")
                    continue
                blob = bytearray()
                for p in pages:
                    blob += o.pages[p]
                base = off - pages[0] * self.page
                out.append(bytes(blob[base:base + length]))
            self._maybe_evict()
            return out

    # -- pinning (kvcache policy) ---------------------------------------
    def pin(self, oid: str, off: int, length: int) -> None:
        """Make [off, off+length) resident and bump each page's pin
        refcount; pinned pages are exempt from LRU eviction until the
        matching unpin()."""
        if length <= 0:
            return
        with self._lock:
            o = self._obj(oid)
            pages = list(self._page_range(off, length))
            self._fill_span(oid, o, pages)
            for p in pages:
                o.pins[p] = o.pins.get(p, 0) + 1

    def unpin(self, oid: str, off: int, length: int) -> None:
        """Drop one pin ref per page; at zero the page rejoins the
        LRU.  Unbalanced unpins are a caller bug -> ValueError."""
        if length <= 0:
            return
        with self._lock:
            o = self._objs.get(oid)
            if o is None:
                raise ValueError(f"unpin of uncached object {oid!r}")
            for p in self._page_range(off, length):
                n = o.pins.get(p, 0)
                if n <= 0:
                    raise ValueError(
                        f"unpin without pin: {oid!r} page {p}")
                if n == 1:
                    del o.pins[p]
                else:
                    o.pins[p] = n - 1

    def pinned_bytes(self) -> int:
        with self._lock:
            return sum(len(o.pins) for o in self._objs.values()) \
                * self.page

    def write(self, oid: str, off: int, data: bytes) -> None:
        if not data:
            return
        with self._lock:
            o = self._obj(oid)
            self.stats["write_back"] += 1
            pos = 0
            for p in self._page_range(off, len(data)):
                p_start = p * self.page
                lo = max(off, p_start) - p_start
                hi = min(off + len(data), p_start + self.page) - p_start
                if lo > 0 or hi < self.page:
                    self._fill_page(oid, o, p)     # partial page: RMW
                elif p not in o.valid:
                    self._install(o, p, bytearray(self.page))
                o.pages[p][lo:hi] = data[pos:pos + (hi - lo)]
                o.vlen[p] = max(o.vlen.get(p, 0), hi)
                pos += hi - lo
                if p not in o.dirty:
                    o.dirty.add(p)
                    self._n_dirty += 1
            if self.dirty_bytes() > self.max_dirty:
                self._flush_oldest_dirty()
            self._maybe_evict()

    def discard(self, oid: str, off: int, length: int) -> None:
        """Drop cached pages fully inside [off, off+len) and zero the
        overlap of boundary pages (the caller zeroed the backing)."""
        with self._lock:
            o = self._objs.get(oid)
            if o is None:
                return
            for p in list(self._page_range(off, length)):
                p_start = p * self.page
                lo = max(off, p_start) - p_start
                hi = min(off + length, p_start + self.page) - p_start
                if lo == 0 and hi == self.page:
                    if p in o.valid:
                        self._n_pages -= 1
                    if p in o.dirty:
                        self._n_dirty -= 1
                    o.pages.pop(p, None)
                    o.valid.discard(p)
                    o.dirty.discard(p)
                    o.vlen.pop(p, None)
                    o.pins.pop(p, None)   # discard outranks pins
                elif p in o.valid:
                    o.pages[p][lo:hi] = b"\0" * (hi - lo)

    # -- flush / invalidate ---------------------------------------------
    def _flush_obj(self, oid: str, o: _CachedObject) -> int:
        wrote = 0
        run: list[int] = []
        for p in sorted(o.dirty) + [None]:
            if run and (p is None or p != run[-1] + 1):
                start = run[0] * self.page
                blob = b"".join(bytes(o.pages[q]) for q in run)
                # truncate the run's tail to the last page's known
                # length: writing the zero padding would extend the
                # backing object past its logical size
                tail = o.vlen.get(run[-1], self.page)
                blob = blob[:(len(run) - 1) * self.page + tail]
                self._write(oid, start, blob)
                self.stats["flush_writes"] += 1
                wrote += len(blob)
                run = []
            if p is not None:
                run.append(p)
        self._n_dirty -= len(o.dirty)
        o.dirty.clear()
        return wrote

    def flush(self, oid: str | None = None) -> int:
        """Push dirty pages to the backing store; returns bytes
        written.  MUST run before a cap/lock is surrendered."""
        with self._lock:
            items = [(oid, self._objs[oid])] if oid is not None and \
                oid in self._objs else \
                ([] if oid is not None else list(self._objs.items()))
            return sum(self._flush_obj(k, o) for k, o in items
                       if o.dirty)

    def _flush_oldest_dirty(self) -> None:
        for oid, o in self._objs.items():      # LRU order
            if o.dirty:
                self._flush_obj(oid, o)
                return

    def invalidate(self, oid: str | None = None,
                   discard_dirty: bool = False) -> None:
        """Drop cached state.  Dirty pages are flushed first unless
        the caller explicitly discards them (rollback/resize paths)."""
        with self._lock:
            oids = [oid] if oid is not None else list(self._objs)
            for k in oids:
                o = self._objs.get(k)
                if o is None:
                    continue
                if o.dirty and not discard_dirty:
                    self._flush_obj(k, o)
                self._n_pages -= len(o.valid)
                self._n_dirty -= len(o.dirty)
                del self._objs[k]

    def _maybe_evict(self) -> None:
        """LRU eviction of clean UNPINNED pages once past max_size
        (pinned pages are promised-resident until unpin)."""
        while self.cached_bytes() > self.max_size:
            for oid, o in self._objs.items():
                clean = [p for p in o.valid
                         if p not in o.dirty and not o.pins.get(p)]
                if clean:
                    for p in clean:
                        o.pages.pop(p, None)
                        o.valid.discard(p)
                        o.vlen.pop(p, None)
                        self._n_pages -= 1
                        self.stats["evicted_pages"] += 1
                    if not o.pages:
                        del self._objs[oid]
                    break
            else:
                # everything is dirty: flush the oldest, then retry
                before = self.dirty_bytes()
                self._flush_oldest_dirty()
                if self.dirty_bytes() >= before:
                    return                      # cannot make progress
