"""rbd live migration: prepare/execute/commit/abort with client IO
running against the destination throughout (VERDICT r4 #7; ref:
src/librbd/api/Migration.cc)."""
import numpy as np
import pytest

from ceph_tpu.rbd import RBD, Image, RBDError
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    r = c.rados()
    r.pool_create("rbd-a", pg_num=8)
    r.pool_create("rbd-b", pg_num=8)
    yield c, r
    c.shutdown()


def mk_image(r, pool, name, mib=4, seed=1):
    io = r.open_ioctx(pool)
    RBD().create(io, name, mib << 20, order=20)   # 1 MiB objects
    img = Image(io, name)
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, mib << 20, dtype=np.uint8).tobytes()
    img.write(0, data)
    img.flush()
    img.release_lock()
    img.close()
    return io, data


def test_migration_full_cycle_with_concurrent_writes(cluster):
    """prepare -> (writes against dst) -> execute -> (more writes) ->
    commit: data identical, source gone, dst standalone."""
    c, r = cluster
    src_io, data = mk_image(r, "rbd-a", "mover", seed=7)
    dst_io = r.open_ioctx("rbd-b")
    rbd = RBD()
    rbd.migration_prepare(src_io, "mover", dst_io, "mover")
    # the source refuses direct opens now
    with pytest.raises(RBDError):
        Image(src_io, "mover")
    # client IO proceeds against the destination BEFORE the copy
    img = Image(dst_io, "mover")
    expected = bytearray(data)
    img.write(123456, b"during-migration-1")
    expected[123456:123456 + 18] = b"during-migration-1"
    assert img.read(0, 1 << 20) == bytes(expected[:1 << 20])
    rbd.migration_execute(dst_io, "mover")
    # ... and after the deep-copy, still against the same open image
    img.write((3 << 20) + 5, b"during-migration-2")
    expected[(3 << 20) + 5:(3 << 20) + 23] = b"during-migration-2"
    img.flush()
    rbd.migration_commit(dst_io, "mover")
    assert img.read(0, len(expected)) == bytes(expected)
    img.close()
    # source is gone (header removed)
    with pytest.raises(RBDError):
        Image(src_io, "mover")
    # destination reopens standalone (no parent link left)
    img2 = Image(dst_io, "mover")
    assert img2.parent is None
    assert img2.read(0, len(expected)) == bytes(expected)
    img2.close()


def test_migration_abort_restores_source(cluster):
    c, r = cluster
    src_io, data = mk_image(r, "rbd-a", "undo", seed=13)
    dst_io = r.open_ioctx("rbd-b")
    rbd = RBD()
    rbd.migration_prepare(src_io, "undo", dst_io, "undo")
    img = Image(dst_io, "undo")
    img.write(0, b"scribble on the destination")
    img.flush()
    img.close()
    rbd.migration_abort(dst_io, "undo")
    # destination gone, source back, bit-identical
    with pytest.raises(RBDError):
        Image(dst_io, "undo")
    img = Image(src_io, "undo")
    assert img.read(0, len(data)) == data
    img.close()


def test_migration_guards(cluster):
    c, r = cluster
    rbd = RBD()
    src_io, _ = mk_image(r, "rbd-a", "guarded", mib=1, seed=3)
    dst_io = r.open_ioctx("rbd-b")
    # snapshotted sources refuse (documented divergence)
    img = Image(src_io, "guarded")
    img.snap_create("s1")
    img.close()
    with pytest.raises(RBDError):
        rbd.migration_prepare(src_io, "guarded", dst_io, "g2")
    img = Image(src_io, "guarded")
    img.snap_remove("s1")
    img.close()
    # an active writer (exclusive lock held) refuses
    img = Image(src_io, "guarded")
    img.write(0, b"live")           # takes the lock
    with pytest.raises(RBDError):
        rbd.migration_prepare(src_io, "guarded", dst_io, "g2")
    img.release_lock()
    img.close()
    # commit before execute refuses
    rbd.migration_prepare(src_io, "guarded", dst_io, "g2")
    with pytest.raises(RBDError):
        rbd.migration_commit(dst_io, "g2")
    rbd.migration_abort(dst_io, "g2")
    assert Image(src_io, "guarded").read(0, 4) == b"live"
